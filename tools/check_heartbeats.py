"""Static check: background-thread loops stay watchable.

Companion to ``check_timed_ops.py`` / ``check_data_paths.py`` /
``check_ckpt_commit.py`` (same lesson: structural invariants rot silently
unless CI asserts them). The live-health plane (``monitor/health.py``) can
only catch a wedged background thread if that thread's loop either touches a
heartbeat (``beat``/``touch``/``begin``/``end``) or bounds every wait — an
unbounded ``while True: q.get()`` in a worker is invisible to the watchdog
AND un-joinable at shutdown. This AST walk (no package imports, runs
anywhere) asserts, for every file in ``runtime/resilience/`` plus
``runtime/data_pipeline/prefetch.py``:

  * every function used as a ``threading.Thread(target=...)`` (resolved
    through module functions, ``self._method`` attributes, and one level of
    plain-name aliasing) is a KNOWN WORKER;
  * every ``while`` loop inside a known worker (including its nested helper
    functions, and the methods it calls on ``self``) contains — directly or
    via a helper defined in the same scope — a heartbeat call or a bounded
    wait (a call with a ``timeout`` argument, ``*_nowait``, or ``sleep``).

A tier-1 test (``tests/test_health.py``) runs this on every CI pass, so a
new background loop cannot silently become unwatchable.
"""

import ast
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_PKG = os.path.join(_HERE, os.pardir, "deepspeed_tpu")

DEFAULT_TARGETS = (
    os.path.join(_PKG, "runtime", "resilience"),
    os.path.join(_PKG, "runtime", "data_pipeline", "prefetch.py"),
)

# heartbeat surface of monitor/health.py
HEARTBEAT_CALLS = {"beat", "touch", "begin", "end"}
# calls that bound a wait by construction
BOUNDED_CALLS = {"sleep", "get_nowait", "put_nowait"}


def _iter_py_files(target):
    if os.path.isfile(target):
        yield target
        return
    for root, _dirs, files in os.walk(target):
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(root, f)


def _func_defs(tree):
    """Every function/method in the module: name -> [nodes] (methods and
    module functions may share names; all candidates are checked)."""
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, []).append(node)
    return out


def _thread_target_names(tree):
    """Names passed as ``target=`` to a ``Thread(...)`` construction:
    bare function names, ``self._method`` attribute names, and plain-name
    aliases (``target = self._background_write`` two lines earlier)."""
    aliases = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            v = node.value
            if isinstance(v, ast.Attribute):
                aliases[node.targets[0].id] = v.attr
            elif isinstance(v, ast.Name):
                aliases[node.targets[0].id] = v.id
    names = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = node.func.attr if isinstance(node.func, ast.Attribute) else \
            (node.func.id if isinstance(node.func, ast.Name) else None)
        if fname != "Thread":
            continue
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            v = kw.value
            if isinstance(v, ast.Attribute):
                names.add(v.attr)
            elif isinstance(v, ast.Name):
                names.add(aliases.get(v.id, v.id))
    return names


def _walk_pruning_defs(node):
    """Like ``ast.walk`` but does not descend into nested function/lambda
    bodies: code inside an uncalled nested def never runs, so a heartbeat
    there must not count as covering the enclosing loop."""
    stack = [node]
    while stack:
        sub = stack.pop()
        yield sub
        for child in ast.iter_child_nodes(sub):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack.append(child)


def _calls_in(node, skip_nested_defs=False):
    """(bare names + attribute names of call targets, whether any call
    carries a bounded wait) inside ``node``. With ``skip_nested_defs`` the
    scan stays in the directly-executed body (nested defs pruned) — their
    contribution comes through helper resolution when they are CALLED."""
    names, bounded = set(), False
    walker = _walk_pruning_defs(node) if skip_nested_defs else ast.walk(node)
    for sub in walker:
        if isinstance(sub, ast.Call):
            f = sub.func
            fname = f.attr if isinstance(f, ast.Attribute) else \
                (f.id if isinstance(f, ast.Name) else None)
            if fname is not None:
                names.add(fname)
                if fname in BOUNDED_CALLS:
                    bounded = True
            if any(kw.arg == "timeout" for kw in sub.keywords):
                bounded = True
    return names, bounded


def _loop_ok(loop, helper_defs):
    """A loop is watchable when its body touches a heartbeat or a bounded
    wait — directly, or through a helper function visible in scope. Nested
    defs in the body are pruned from the direct scan (defining a heartbeat
    is not calling one)."""
    names, bounded = _calls_in(loop, skip_nested_defs=True)
    if bounded or names & HEARTBEAT_CALLS:
        return True
    # one level of helper resolution: `put(item)` where the sibling-scoped
    # `put` contains the bounded wait / heartbeat
    for n in names:
        for helper in helper_defs.get(n, ()):
            h_names, h_bounded = _calls_in(helper)
            if h_bounded or h_names & HEARTBEAT_CALLS:
                return True
    return False


def _worker_closure(defs, roots):
    """Worker functions plus everything they call that is defined in the
    same module (the thread executes those bodies too)."""
    seen, frontier = set(), list(roots)
    while frontier:
        name = frontier.pop()
        if name in seen or name not in defs:
            continue
        seen.add(name)
        for node in defs[name]:
            called, _ = _calls_in(node)
            frontier.extend(called - seen)
    return seen


def check(targets=DEFAULT_TARGETS):
    """Return a list of human-readable violations (empty == clean)."""
    violations = []
    for target in targets:
        for path in _iter_py_files(target):
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
            defs = _func_defs(tree)
            workers = _thread_target_names(tree)
            if not workers:
                continue
            for fn_name in sorted(_worker_closure(defs, workers)):
                for fn in defs.get(fn_name, ()):
                    for sub in ast.walk(fn):
                        if not isinstance(sub, ast.While):
                            continue
                        if not _loop_ok(sub, defs):
                            rel = os.path.relpath(path, os.path.join(_HERE, os.pardir))
                            violations.append(
                                f"{rel}:{sub.lineno} `while` loop in worker-thread "
                                f"function '{fn_name}' has neither a heartbeat "
                                f"(beat/touch/begin/end) nor a bounded wait "
                                f"(timeout=/sleep/*_nowait) — the stall watchdog "
                                f"cannot see it and shutdown cannot bound it")
    return violations


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    targets = tuple(argv) if argv else DEFAULT_TARGETS
    violations = check(targets)
    if violations:
        print("check_heartbeats: FAILED")
        for v in violations:
            print(f"  - {v}")
        return 1
    print("check_heartbeats: all worker-thread loops are heartbeat-covered or bounded")
    return 0


if __name__ == "__main__":
    sys.exit(main())
