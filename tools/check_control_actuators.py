#!/usr/bin/env python
"""AST gate: control-plane actuators are reachable ONLY from the
controller's decision-applying helpers, and every decision site logs.

The self-driving serving loop (``deepspeed_tpu/serving/control/``) is only
auditable if actuations cannot bypass it: a stray ``replica.drain()`` in a
request handler, or an admission override applied from a bench script
inside the package, would mutate the fleet with no decision record. Three
rules keep the loop closed:

  1. Anywhere in ``deepspeed_tpu/``, a call to a GATED actuator method
     (``pause`` / ``resume`` / ``drain`` / ``undrain`` / ``restart`` /
     ``set_depth_override`` / ``clear_depth_override`` /
     ``set_spec_params``) is a violation unless (a) it sits inside a
     ``serving/control/`` function named ``_apply_*`` (the sanctioned
     decision-applying helpers), or (b) the calling module itself DEFINES
     a function of that name (the defining module and its internal
     plumbing — e.g. ``replica.py``'s goodput-ledger ``resume`` calls).

  2. Inside ``serving/control/``, a call to a ``KernelAutotuner`` sweep
     entry point (``tune_paged`` / ``tune_paged_decode`` / ``tune_flash``
     / ``tune_grouped`` / ``tune_all`` / ``sweep``) must sit inside an
     ``_apply_*`` helper — a policy or sensor path must never launch
     device work.

  3. Every ``_apply_*`` function in ``serving/control/`` must contain at
     least one ``.emit(`` call — an actuation without a decision record
     is structurally impossible.

Tests and tools outside the package are exempt on purpose: drills and
operators may pause/restart replicas; the invariant is about the serving
package's own request/sensor paths.

Run from the repo root (or pass a package dir):

    python tools/check_control_actuators.py [pkg_dir]

Exit 0 = clean, 1 = violations (printed one per line). Wired into tier-1
via ``tests/test_control_plane.py``.
"""

import ast
import os
import sys

DEFAULT_PKG_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               os.pardir, "deepspeed_tpu")

GATED_ACTUATORS = frozenset({
    "pause", "resume", "drain", "undrain", "restart",
    "set_depth_override", "clear_depth_override", "set_spec_params",
})

TUNER_CALLS = frozenset({
    "tune_paged", "tune_paged_decode", "tune_flash", "tune_grouped",
    "tune_all", "sweep",
})


def _is_control_file(rel: str) -> bool:
    rel = rel.replace(os.sep, "/")
    return "serving/control/" in rel or rel.startswith("serving/control/")


def _defined_names(tree: ast.AST):
    """Every function/method name defined anywhere in the module."""
    return {n.name for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def find_violations(pkg_dir: str):
    violations = []
    for root, _dirs, files in os.walk(pkg_dir):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, pkg_dir)
            with open(path, encoding="utf-8") as f:
                src = f.read()
            try:
                tree = ast.parse(src)
            except SyntaxError as e:
                violations.append((rel, e.lineno or 0, "<unparseable>",
                                   f"syntax error: {e.msg}"))
                continue
            lines = src.splitlines()
            in_control = _is_control_file(rel)
            defined = _defined_names(tree)

            def flag(node, why):
                snippet = (lines[node.lineno - 1].strip()
                           if 0 < node.lineno <= len(lines) else "")
                violations.append((rel, node.lineno, snippet, why))

            def walk(node, func_stack):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    func_stack = func_stack + [node.name]
                    if in_control and node.name.startswith("_apply_"):
                        # rule 3: the helper must emit a decision record
                        emits = [c for c in ast.walk(node)
                                 if isinstance(c, ast.Call)
                                 and isinstance(c.func, ast.Attribute)
                                 and c.func.attr == "emit"]
                        if not emits:
                            flag(node, f"decision helper {node.name} never "
                                       "emits a decision record (rule 3)")
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute):
                    name = node.func.attr
                    in_apply = any(f.startswith("_apply_") for f in func_stack)
                    if name in GATED_ACTUATORS:
                        sanctioned = (in_control and in_apply) or name in defined
                        if not sanctioned:
                            flag(node, f"actuator .{name}() outside a "
                                       "serving/control/ _apply_* helper (rule 1)")
                    if in_control and name in TUNER_CALLS and not in_apply:
                        flag(node, f"autotuner .{name}() outside an _apply_* "
                                   "helper (rule 2)")
                for child in ast.iter_child_nodes(node):
                    walk(child, func_stack)

            walk(tree, [])
    return violations


def check(pkg_dir: str = DEFAULT_PKG_DIR):
    return find_violations(pkg_dir)


def main(argv) -> int:
    pkg_dir = argv[1] if len(argv) > 1 else DEFAULT_PKG_DIR
    violations = find_violations(pkg_dir)
    if violations:
        print(f"check_control_actuators: {len(violations)} violation(s):")
        for rel, lineno, snippet, why in violations:
            print(f"  {rel}:{lineno}: {why}\n      {snippet}")
        return 1
    print("check_control_actuators: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
