"""Static check: ``train_batch``'s data-dependent paths all route through the
SINGLE host-work helper ``DeepSpeedEngine._host_prepare_batch``.

Companion to ``check_timed_ops.py`` (same lesson: structural invariants rot
silently unless CI asserts them). The prefetch subsystem
(``runtime/data_pipeline/prefetch.py``) runs the host side of batch assembly
— post-process, gas-major stacking, curriculum truncation, PLD theta — in a
background worker; if a second copy of that logic ever grows back inside
``train_batch`` / ``_offload_train_batch``, the prefetched and synchronous
paths drift apart and losses stop being bit-identical. This AST walk (no
package imports, runs anywhere) asserts:

  * ``_host_prepare_batch`` exists and actually contains the assembly logic
    (post-process + stack + curriculum calls);
  * ``train_batch`` calls the helper and contains NO direct assembly calls;
  * ``_offload_train_batch`` contains neither assembly calls nor a second
    helper call (its batches arrive prepared AND placed);
  * ``prefetching_loader`` wires the worker to the same helper.

A tier-1 test (``tests/test_prefetch.py``) runs this on every CI pass.
"""

import ast
import os
import sys

DEFAULT_ENGINE_PY = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir,
                                 "deepspeed_tpu", "runtime", "engine.py")

HOST_HELPER = "_host_prepare_batch"
# call targets (attribute or bare name) that ARE the host assembly logic —
# allowed only inside the helper (and the eager forward(), which handles one
# microbatch at a time and is not a train_batch data path). Scheduler
# STATE-ADVANCE calls (update_difficulty/update_state) are deliberately not
# listed: train_batch runs them as main-thread housekeeping on the
# prefetched path — they change no batch content
ASSEMBLY_CALLS = ("_data_post_process_func", "_apply_curriculum", "stack")
# train_batch data paths: must stay free of assembly logic
DATA_PATHS = ("train_batch", "_offload_train_batch")


def _called_names(fn_node):
    """All call targets inside ``fn_node``: bare names and attribute names."""
    out = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name):
                out.add(f.id)
            elif isinstance(f, ast.Attribute):
                out.add(f.attr)
    return out


def _engine_methods(path):
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "DeepSpeedEngine":
            return {n.name: n for n in node.body if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    return {}


def check(path=DEFAULT_ENGINE_PY):
    """Return a list of human-readable violations (empty == clean)."""
    methods = _engine_methods(path)
    violations = []
    if not methods:
        return [f"class DeepSpeedEngine not found in {path}"]

    helper = methods.get(HOST_HELPER)
    if helper is None:
        return [f"{HOST_HELPER} missing from DeepSpeedEngine ({path})"]
    helper_calls = _called_names(helper)
    for required in ("_data_post_process_func", "stack", "_apply_curriculum"):
        if required not in helper_calls:
            violations.append(f"{HOST_HELPER} no longer calls {required!r} — the assembly "
                              "logic moved; update this gate with it")

    for name in DATA_PATHS:
        fn = methods.get(name)
        if fn is None:
            violations.append(f"{name} missing from DeepSpeedEngine")
            continue
        leaked = sorted(_called_names(fn) & set(ASSEMBLY_CALLS))
        if leaked:
            violations.append(f"{name} calls {leaked} directly — host batch assembly must "
                              f"route through {HOST_HELPER} (prefetch/sync parity)")
    tb = methods.get("train_batch")
    if tb is not None and HOST_HELPER not in _called_names(tb):
        violations.append(f"train_batch does not call {HOST_HELPER} — the synchronous "
                          "path must use the shared helper")
    ob = methods.get("_offload_train_batch")
    if ob is not None and HOST_HELPER in _called_names(ob):
        violations.append(f"_offload_train_batch calls {HOST_HELPER} — its batches arrive "
                          "prepared and placed; preparing twice double-applies hooks")
    pl = methods.get("prefetching_loader")
    if pl is None:
        violations.append("prefetching_loader missing from DeepSpeedEngine")
    elif HOST_HELPER not in _called_names(pl):
        violations.append(f"prefetching_loader does not wire the worker to {HOST_HELPER}")
    return violations


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    path = argv[0] if argv else DEFAULT_ENGINE_PY
    violations = check(path)
    if violations:
        print("check_data_paths: FAILED")
        for v in violations:
            print(f"  - {v}")
        return 1
    print("check_data_paths: train_batch data paths route through the single host-work helper")
    return 0


if __name__ == "__main__":
    sys.exit(main())
