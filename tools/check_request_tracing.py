"""Static check: request-id discipline in the serving request plane.

Companion to ``check_gateway_api.py`` (same lesson: structural invariants
rot silently unless CI asserts them). Two invariants, both AST-checked with
no package imports so the gate runs anywhere:

  1. **One respond helper.** Every HTTP response ``serving/gateway.py``
     writes — success, 400/404/429/503/504, the catch-all 500, the GET
     endpoints, the SSE header block — must go through the single
     id-attaching helper (``_respond``): no call to ``send_response`` /
     ``send_header`` / ``end_headers`` may exist outside it. The moment an
     error branch added later writes its own status line, the
     ``X-Request-Id`` echo contract silently breaks for exactly the
     responses (errors) where correlation matters most.

  2. **Every serving span carries the request id.** Any tracer emission
     from ``deepspeed_tpu/serving/`` (``.instant(...)`` / ``.span(...)``
     keyword form, ``.complete(...)`` args-dict form) must carry a
     ``request_id`` field — a span that cannot be joined back to a request
     is dead weight in a request-scoped trace.

A tier-1 test (``tests/test_request_tracing.py``) runs this on every CI
pass.
"""

import ast
import os
import sys

DEFAULT_SERVING_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir,
                                   "deepspeed_tpu", "serving")

# the ONE function allowed to write response lines/headers
RESPOND_HELPER = "_respond"
RAW_RESPONSE_CALLS = ("send_response", "send_header", "end_headers")

# tracer emitters that take the id as a keyword vs inside an args= dict
KEYWORD_EMITTERS = ("instant", "span")
ARGSDICT_EMITTERS = ("complete",)


def _call_attr_name(node):
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


def _args_dict_has_request_id(node):
    """True when a ``.complete(...)`` call passes ``args={...}`` as a dict
    LITERAL containing a ``"request_id"`` key (the only statically
    checkable form — emission sites must keep it literal)."""
    for kw in node.keywords:
        if kw.arg == "args" and isinstance(kw.value, ast.Dict):
            for key in kw.value.keys:
                if isinstance(key, ast.Constant) and key.value == "request_id":
                    return True
    return False


def _check_gateway_respond_helper(path, src, tree):
    """Invariant 1: raw response-writing calls only inside RESPOND_HELPER."""
    violations = []
    lines = src.splitlines()

    class Walker(ast.NodeVisitor):
        def __init__(self):
            self.stack = []

        def _visit_func(self, node):
            self.stack.append(node.name)
            self.generic_visit(node)
            self.stack.pop()

        visit_FunctionDef = _visit_func
        visit_AsyncFunctionDef = _visit_func

        def visit_Call(self, node):
            name = _call_attr_name(node)
            if name in RAW_RESPONSE_CALLS and RESPOND_HELPER not in self.stack:
                snippet = (lines[node.lineno - 1].strip()
                           if node.lineno <= len(lines) else "")
                violations.append(
                    (os.path.basename(path), node.lineno, snippet,
                     f"raw '{name}' outside the {RESPOND_HELPER} helper "
                     f"(X-Request-Id echo bypassed)"))
            self.generic_visit(node)

    Walker().visit(tree)
    helper_defined = any(isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                         and n.name == RESPOND_HELPER for n in ast.walk(tree))
    if not helper_defined:
        violations.append((os.path.basename(path), 1, "",
                           f"no {RESPOND_HELPER} helper defined in gateway.py"))
    return violations


def _check_span_request_ids(path, src, tree):
    """Invariant 2: serving-plane tracer emissions carry request_id."""
    violations = []
    lines = src.splitlines()
    for node in ast.walk(tree):
        name = _call_attr_name(node)
        if name is None:
            continue
        why = None
        if name in KEYWORD_EMITTERS:
            if not any(kw.arg == "request_id" for kw in node.keywords):
                why = f"'{name}' emission without a request_id= keyword"
        elif name in ARGSDICT_EMITTERS:
            if not _args_dict_has_request_id(node):
                why = (f"'{name}' emission without a literal "
                       f"args={{'request_id': ...}} entry")
        if why:
            snippet = (lines[node.lineno - 1].strip()
                       if node.lineno <= len(lines) else "")
            violations.append((os.path.basename(path), node.lineno, snippet, why))
    return violations


def find_violations(serving_dir=DEFAULT_SERVING_DIR):
    """[(file, lineno, snippet, why)] across the serving package."""
    violations = []
    for root, _dirs, files in os.walk(serving_dir):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            with open(path) as f:
                src = f.read()
            tree = ast.parse(src, filename=path)
            if fname == "gateway.py":
                violations.extend(_check_gateway_respond_helper(path, src, tree))
            violations.extend(_check_span_request_ids(path, src, tree))
    return violations


def check(serving_dir=DEFAULT_SERVING_DIR):
    """Return the violation list (empty = the request plane is clean)."""
    return find_violations(serving_dir)


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    serving_dir = argv[0] if argv else DEFAULT_SERVING_DIR
    bad = check(serving_dir)
    if bad:
        print(f"check_request_tracing: request-id discipline violated in {serving_dir}:")
        for rel, lineno, snippet, why in bad:
            print(f"  {rel}:{lineno}: {why}: {snippet}")
        return 1
    print("check_request_tracing: every response path attaches X-Request-Id and "
          "every serving span carries request_id")
    return 0


if __name__ == "__main__":
    sys.exit(main())
