"""Static check: tenant-label cardinality discipline.

Companion to ``check_metric_names.py`` (same lesson: structural invariants
rot silently unless CI asserts them). Unbounded tenant-cardinality
Prometheus rows are a fleet-killer: one hostile client inventing tenant
ids per request grows the scrape (and every downstream TSDB) without
bound. The ONLY sanctioned source of a ``tenant`` metric label is the
bounded top-K aggregator in ``serving/metering.py`` (``TenantMeter
.gauge_rows``: top-K tenants by spend + ONE aggregated ``other`` row, so
``/metrics`` never carries more than K+1 distinct tenant label values).

Two rules, AST-checked with no package imports so the gate runs anywhere:

  1. **No tenant-labelled gauge rows outside metering.py.** A labelled
     exporter row is the 3-tuple ``(name, {labels}, value)`` (the
     ``HealthPlane.set_gauge_provider`` shape): any such tuple literal
     whose label dict carries a ``"tenant"`` key, anywhere under
     ``deepspeed_tpu/`` except ``serving/metering.py``, is a violation —
     route the row through the meter's aggregator instead.
  2. **No tenant-named registry metrics outside metering.py.** Any
     ``counter``/``gauge``/``histogram`` registration whose literal (or
     f-string head) name contains ``tenant`` outside ``serving/metering.py``
     is a violation — per-tenant series belong behind the top-K bound,
     and an f-string interpolating a tenant id into a metric NAME is the
     same unbounded-cardinality bug wearing a different hat.

A tier-1 test (``tests/test_tenant_metering.py``) runs this on every CI
pass, with the usual drift-catch (a synthetic violating tree must fail).
"""

import ast
import os
import sys

DEFAULT_PKG_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir,
                               "deepspeed_tpu")

# the one module allowed to emit tenant-labelled rows / tenant-named metrics
ALLOWED_MODULE = os.path.join("serving", "metering.py")

REGISTRATION_CALLS = ("counter", "gauge", "histogram")


def _dict_has_tenant_key(node) -> bool:
    if not isinstance(node, ast.Dict):
        return False
    return any(isinstance(k, ast.Constant) and k.value == "tenant"
               for k in node.keys)


def _is_tenant_labelled_row(node) -> bool:
    """A ``(name, {...'tenant'...}, value)`` gauge-row tuple literal."""
    if not isinstance(node, ast.Tuple) or len(node.elts) != 3:
        return False
    name = node.elts[0]
    name_ok = (isinstance(name, ast.Constant) and isinstance(name.value, str)) \
        or isinstance(name, ast.JoinedStr)
    return name_ok and _dict_has_tenant_key(node.elts[1])


def _registration_name(node):
    """The literal/f-string-head metric name of a registration call, or
    None when the call is not a registration (or the name is dynamic)."""
    if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr in REGISTRATION_CALLS and node.args):
        return None
    arg = node.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.JoinedStr) and arg.values \
            and isinstance(arg.values[0], ast.Constant) \
            and isinstance(arg.values[0].value, str):
        return arg.values[0].value
    return None


def find_violations(pkg_dir=DEFAULT_PKG_DIR):
    """[(relpath, lineno, snippet, why)] for every tenant-label escape."""
    violations = []
    for root, _dirs, files in os.walk(pkg_dir):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, pkg_dir)
            if rel == ALLOWED_MODULE:
                continue
            with open(path) as f:
                src = f.read()
            tree = ast.parse(src, filename=path)
            lines = src.splitlines()

            def flag(node, why):
                snippet = lines[node.lineno - 1].strip() if node.lineno <= len(lines) else ""
                violations.append((rel, node.lineno, snippet, why))

            for node in ast.walk(tree):
                if _is_tenant_labelled_row(node):
                    flag(node, "tenant-labelled gauge row outside serving/metering.py "
                               "— route it through TenantMeter's bounded top-K "
                               "aggregator")
                name = _registration_name(node)
                if name is not None and "tenant" in name:
                    flag(node, f"metric registration {name!r} carries 'tenant' "
                               "outside serving/metering.py — per-tenant series "
                               "belong behind the top-K bound")
    return violations


def check(pkg_dir=DEFAULT_PKG_DIR):
    """Return the violation list (empty = every tenant label is bounded)."""
    return find_violations(pkg_dir)


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    pkg_dir = argv[0] if argv else DEFAULT_PKG_DIR
    bad = check(pkg_dir)
    if bad:
        print(f"check_tenant_labels: unbounded tenant-label escapes in {pkg_dir}:")
        for rel, lineno, snippet, why in bad:
            print(f"  {rel}:{lineno}: {why}\n      {snippet}")
        return 1
    print("check_tenant_labels: every tenant-labelled metric routes through "
          "the bounded top-K aggregator in serving/metering.py")
    return 0


if __name__ == "__main__":
    sys.exit(main())
