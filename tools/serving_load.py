"""FastGen-style continuous-batching LOAD benchmark.

VERDICT r4 missing #3: the repo benched single-batch decode tok/s + TTFT,
but the reference's headline serving claim is SYSTEM throughput under load
(2.3x vLLM at the same latency, rps-vs-latency curves —
``/root/reference/blogs/deepspeed-fastgen/README.md:28,139-144``). This
harness measures exactly that, on the repo's own engine, policy vs policy:

  - **splitfuse**: :class:`DynamicSplitFuseScheduler` — decodes compose
    with chunked prefills every forward, arrivals admit continuously.
  - **static**: the classic static-batching server loop over the SAME
    engine — wait for a batch, prefill whole prompts, decode the batch to
    completion, only then admit the next batch (arrivals wait out the
    drain; heterogeneous generation lengths leave idle slots).

Both policies run the identical Poisson workload (same seed: same arrival
times, prompt lengths, generation lengths) and, being greedy over the same
engine, must produce identical tokens — scheduling changes WHEN work runs,
never WHAT it computes (asserted in tests/test_serving_load.py).

Output: one JSON line — a saturated-throughput comparison plus an
rps-vs-latency curve (p50/p95 per policy per offered rate).

PR 6 grew this harness a second face: a **closed-loop HTTP load
generator** over the serving gateway (``deepspeed_tpu/serving/``).
:func:`run_http_load` drives ``POST /v1/generate`` with a bounded worker
pool that HONORS the workload's arrival times (sleep-until-arrival — an
offered rate is a promise, not a timestamp column) and reports offered vs
achieved rate alongside client-side TTFT/TPOT percentiles and the shed
(429) rate, so a saturated point on the curve is visibly saturated instead
of silently self-pacing. :func:`gateway_latency_curves` sweeps offered
rates into latency-under-load curves and :func:`router_prefix_ab` runs the
prefix-aware-router vs random-placement A/B on the Zipf shared-prefix
workload (same engines, caches cleared between arms — strictly higher
aggregate hit rate is the acceptance bar). CLI: ``python
tools/serving_load.py gateway`` emits both as one JSON line.

PR 15 added the **multi-tenant** face: :func:`make_multi_tenant_workload`
(N Zipf-share tenants + one adversarial hot tenant, per-tenant prefix
pools, rows carry ``tenant`` → sent as ``X-Tenant-Id``) and
:func:`multi_tenant_bench` — closed-loop HTTP with the metering plane
armed, reporting the fairness index, per-tenant client-side TTFT/TPOT and
hit rates, and the hot tenant's compute share (``bench.py``'s
``tenants{...}`` block; CLI ``multi_tenant``).
"""

import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_workload(n_requests, prompt_lo, prompt_hi, new_lo, new_hi, rate_rps, seed=0,
                  uid_base=0):
    """Poisson arrivals (exponential inter-arrival at ``rate_rps``), uniform
    prompt and generation lengths. ``rate_rps=None`` puts every arrival at
    t=0 (saturated / offered-load-infinity mode)."""
    rng = np.random.default_rng(seed)
    if rate_rps is None:
        arrivals = np.zeros(n_requests)
    else:
        arrivals = np.cumsum(rng.exponential(1.0 / rate_rps, size=n_requests))
    work = []
    for i in range(n_requests):
        plen = int(rng.integers(prompt_lo, prompt_hi + 1))
        work.append({
            "uid": uid_base + i,
            "arrival": float(arrivals[i]),
            "prompt": rng.integers(0, 100, size=plen).astype(np.int32),
            "max_new_tokens": int(rng.integers(new_lo, new_hi + 1)),
        })
    return work


def make_shared_prefix_workload(n_requests, n_prefixes, prefix_len, suffix_lo, suffix_hi,
                                new_lo, new_hi, rate_rps=None, seed=0, uid_base=0,
                                zipf_a=1.2, unique=False):
    """Shared-prefix mode (the production shape prefix caching targets): a
    Zipf-sampled pool of ``n_prefixes`` system prompts, each request = one
    pooled prefix + a unique user suffix. ``unique=True`` gives every request
    its own prefix instead (the 0%-hit adversarial control for the A/B).
    Same arrival semantics as :func:`make_workload`."""
    rng = np.random.default_rng(seed)
    if rate_rps is None:
        arrivals = np.zeros(n_requests)
    else:
        arrivals = np.cumsum(rng.exponential(1.0 / rate_rps, size=n_requests))
    pool = [rng.integers(0, 100, size=prefix_len).astype(np.int32) for _ in range(n_prefixes)]
    # Zipf ranks folded into the pool: rank 1 (the hottest system prompt)
    # dominates, the tail shares the rest — the head-heavy reuse profile of
    # real serving traffic
    ranks = (rng.zipf(zipf_a, size=n_requests) - 1) % n_prefixes
    work = []
    for i in range(n_requests):
        prefix = (rng.integers(0, 100, size=prefix_len).astype(np.int32) if unique
                  else pool[int(ranks[i])])
        suffix = rng.integers(0, 100, size=int(rng.integers(suffix_lo, suffix_hi + 1))).astype(np.int32)
        work.append({
            "uid": uid_base + i,
            "arrival": float(arrivals[i]),
            "prompt": np.concatenate([prefix, suffix]),
            "max_new_tokens": int(rng.integers(new_lo, new_hi + 1)),
        })
    return work


def make_multi_tenant_workload(n_requests, n_tenants=4, zipf_a=1.3,
                               hot_tenant="hot", hot_share=0.4,
                               n_prefixes_per_tenant=2, prefix_len=24,
                               suffix_lo=4, suffix_hi=10, new_lo=3, new_hi=8,
                               hot_new_mult=2, rate_rps=None, seed=0, uid_base=0):
    """Multi-tenant workload (the ISSUE 15 shape): ``n_tenants`` tenants
    with Zipf-skewed traffic shares plus ONE adversarial hot tenant taking
    ``hot_share`` of all requests with ``hot_new_mult``x longer generations
    — the starve-the-rest scenario the fairness observability exists to
    make visible. Each tenant owns its own small prefix pool (its few-shot
    templates), so per-tenant hit rates and cross-tenant hit attribution
    are both meaningful. Rows carry ``tenant`` (sent as ``X-Tenant-Id`` by
    the HTTP load generator); arrival semantics as :func:`make_workload`."""
    rng = np.random.default_rng(seed)
    if rate_rps is None:
        arrivals = np.zeros(n_requests)
    else:
        arrivals = np.cumsum(rng.exponential(1.0 / rate_rps, size=n_requests))
    names = [f"t{i}" for i in range(n_tenants)]
    pools = {t: [rng.integers(0, 100, size=prefix_len).astype(np.int32)
                 for _ in range(n_prefixes_per_tenant)]
             for t in names + [hot_tenant]}
    ranks = (rng.zipf(zipf_a, size=n_requests) - 1) % n_tenants
    hot_mask = rng.random(n_requests) < hot_share
    work = []
    for i in range(n_requests):
        tenant = hot_tenant if hot_mask[i] else names[int(ranks[i])]
        prefix = pools[tenant][int(rng.integers(len(pools[tenant])))]
        suffix = rng.integers(0, 100, size=int(rng.integers(suffix_lo, suffix_hi + 1))).astype(np.int32)
        new = int(rng.integers(new_lo, new_hi + 1))
        if tenant == hot_tenant:
            new *= hot_new_mult
        work.append({
            "uid": uid_base + i,
            "arrival": float(arrivals[i]),
            "tenant": tenant,
            "prompt": np.concatenate([prefix, suffix]),
            "max_new_tokens": new,
        })
    return work


def run_splitfuse(engine, workload, token_budget=None, stats_out=None):
    """Open-loop load over DynamicSplitFuseScheduler. Returns
    ({uid: (latency_s, tokens)}, makespan_s). ``stats_out`` (a dict) receives
    the scheduler's prefill fed/skipped token counts when provided."""
    from deepspeed_tpu.inference.v2 import DynamicSplitFuseScheduler

    sched = DynamicSplitFuseScheduler(engine, token_budget=token_budget)
    work = sorted(workload, key=lambda r: r["arrival"])
    n = len(work)
    done = {}
    seen_finished = set()
    i = 0
    t0 = time.time()
    while len(done) < n:
        now = time.time() - t0
        while i < n and work[i]["arrival"] <= now:
            r = work[i]
            sched.submit(r["uid"], r["prompt"], max_new_tokens=r["max_new_tokens"])
            i += 1
        if sched.has_work:
            processed = sched.step()
            if processed == 0 and i >= n:
                raise RuntimeError("splitfuse load stalled with arrivals exhausted")
        elif i < n:
            time.sleep(max(0.0, min(0.005, work[i]["arrival"] - (time.time() - t0))))
            continue
        t_now = time.time() - t0
        for uid in sched.finished - seen_finished:
            seen_finished.add(uid)
            done[uid] = t_now
    makespan = time.time() - t0
    results = sched.results
    if stats_out is not None:
        stats_out.update(sched.stats)
        if sched.speculating:
            stats_out["spec"] = dict(sched.spec_stats)
    arrival = {r["uid"]: r["arrival"] for r in work}
    return {u: (done[u] - arrival[u], results[u]) for u in done}, makespan


def run_static(engine, workload, batch_size, decode_horizon=32):
    """Classic static-batching server over the same engine mechanism: admit
    up to ``batch_size`` ARRIVED requests, prefill each whole prompt, decode
    the batch lock-step to completion, flush, repeat. Later arrivals wait
    out the entire drain — the bubble Dynamic SplitFuse removes."""
    work = sorted(workload, key=lambda r: r["arrival"])
    n = len(work)
    done = {}
    queue = []
    i = 0
    t0 = time.time()
    while len(done) < n:
        now = time.time() - t0
        while i < n and work[i]["arrival"] <= now:
            queue.append(work[i])
            i += 1
        if not queue:
            time.sleep(max(0.0, min(0.005, work[i]["arrival"] - (time.time() - t0))))
            continue
        batch = queue[:batch_size]
        del queue[:batch_size]
        gen = {}
        remaining = {}
        for r in batch:  # whole-prompt prefill, one sequence per put
            tok = engine.put([r["uid"]], [r["prompt"]], sample="greedy")
            gen[r["uid"]] = [int(np.asarray(tok).reshape(-1)[0])]
            remaining[r["uid"]] = r["max_new_tokens"] - 1
        # textbook static batching: the WHOLE batch decodes lock-step until
        # the LONGEST request finishes — already-finished slots keep burning
        # decode steps whose tokens are discarded (the idle-slot bubble that
        # Dynamic SplitFuse removes), and arrivals wait out the drain
        uids = [r["uid"] for r in batch]
        steps_left = max(remaining.values())
        while steps_left > 0:
            h = min(decode_horizon, steps_left)
            h = 1 << (h.bit_length() - 1)  # power-of-two horizons: bounded compiles
            toks = np.asarray(engine.decode(
                uids, [np.asarray([gen[u][-1]], np.int32) for u in uids], h))
            for u, row in zip(uids, toks):
                take = min(h, remaining[u])
                gen[u].extend(int(t) for t in row[:take])
                remaining[u] -= take
            steps_left -= h
        t_done = time.time() - t0
        for r in batch:
            engine.flush(r["uid"])
            done[r["uid"]] = (t_done - r["arrival"], gen[r["uid"]])
    return done, time.time() - t0


def _latency_stats(done):
    lats = np.asarray([v[0] for v in done.values()])
    return {"p50_ms": round(float(np.percentile(lats, 50)) * 1000, 1),
            "p95_ms": round(float(np.percentile(lats, 95)) * 1000, 1)}


def build_engine(on_tpu, prefix_cache=False, speculative=None, host_blocks=None):
    import jax.numpy as jnp
    from deepspeed_tpu.models import TransformerConfig, TransformerLM
    from deepspeed_tpu.inference.v2 import (DSStateManagerConfig, HostTierConfig,
                                            InferenceEngineV2, PrefixCacheConfig,
                                            RaggedInferenceEngineConfig)

    if on_tpu:
        cfg = TransformerConfig(vocab_size=32000, hidden_size=2048, num_layers=12,
                                num_heads=16, num_kv_heads=16, intermediate_size=5632,
                                max_seq_len=2048, norm="rmsnorm", positions="rotary",
                                mlp="swiglu", dtype=jnp.bfloat16, attention_impl="flash")
        sm = DSStateManagerConfig(max_tracked_sequences=32, max_ragged_batch_size=512,
                                  max_ragged_sequence_count=32, max_context=768)
        icfg = RaggedInferenceEngineConfig(kv_block_size=128, num_kv_blocks=224,
                                           kv_dtype="int8", state_manager=sm)
    else:
        cfg = TransformerConfig(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                                num_kv_heads=2, intermediate_size=128, max_seq_len=256,
                                dtype=jnp.float32, attention_impl="reference")
        sm = DSStateManagerConfig(max_tracked_sequences=8, max_ragged_batch_size=64,
                                  max_ragged_sequence_count=8, max_context=64)
        icfg = RaggedInferenceEngineConfig(kv_block_size=8, num_kv_blocks=80,
                                           kv_dtype=jnp.float32, state_manager=sm,
                                           use_pallas_kernels="never")
    # host_blocks arms the pinned host tier (required transport for the
    # disaggregated KV handoff — install_prefix_kv adopts host-tier nodes)
    icfg.prefix_cache = PrefixCacheConfig(
        enabled=bool(prefix_cache) or host_blocks is not None,
        host_tier=(HostTierConfig(host_blocks=int(host_blocks))
                   if host_blocks else None))
    if speculative is not None:
        icfg.speculative = speculative
    return InferenceEngineV2(TransformerLM(cfg), icfg)


def serving_load_bench(on_tpu, n_requests=None, seed=0):
    """Full comparison: saturated throughput + rps/latency curve. Returns the
    result dict (also usable from bench_ladder)."""
    engine = build_engine(on_tpu)
    if on_tpu:
        n = n_requests or 64
        shape = dict(prompt_lo=128, prompt_hi=448, new_lo=32, new_hi=128)
        static_bs, budget = 16, 512
        rate_mults = (0.5, 1.0, 2.0)
    else:
        n = n_requests or 16
        shape = dict(prompt_lo=8, prompt_hi=24, new_lo=4, new_hi=12)
        static_bs, budget = 4, 32
        rate_mults = (1.0,)

    # warmup pass compiles every batch-shape bucket both policies touch, so
    # the measured passes time scheduling, not XLA compiles
    warm = make_workload(n, rate_rps=None, seed=seed, uid_base=0, **shape)
    run_splitfuse(engine, warm, token_budget=budget)
    run_static(engine, warm, static_bs)

    # --- saturated: all requests offered at t=0; throughput = N / makespan ---
    sat = make_workload(n, rate_rps=None, seed=seed, uid_base=10_000, **shape)
    sf_done, sf_span = run_splitfuse(engine, sat, token_budget=budget)
    st_done, st_span = run_static(
        engine, [dict(r, uid=r["uid"] + 10_000) for r in sat], static_bs)
    sf_rps, st_rps = n / sf_span, n / st_span
    result = {
        "config": "fastgen_splitfuse_vs_static",
        "n_requests": n,
        "saturated": {"splitfuse_rps": round(sf_rps, 2), "static_rps": round(st_rps, 2),
                      "speedup": round(sf_rps / st_rps, 3)},
        "curve": [],
    }

    # --- open-loop curve: offered rates around splitfuse's saturated rps ---
    for mi, mult in enumerate(rate_mults):
        rate = sf_rps * mult
        wl = make_workload(n, rate_rps=rate, seed=seed + 1 + mi,
                           uid_base=50_000 + 20_000 * mi, **shape)
        sf_d, sf_s = run_splitfuse(engine, wl, token_budget=budget)
        st_d, st_s = run_static(
            engine, [dict(r, uid=r["uid"] + 10_000) for r in wl], static_bs)
        result["curve"].append({
            "offered_rps": round(rate, 2),
            "splitfuse": dict(rps=round(n / sf_s, 2), **_latency_stats(sf_d)),
            "static": dict(rps=round(n / st_s, 2), **_latency_stats(st_d)),
        })
    return result


def shared_prefix_ab(on_tpu, n_requests=None, seed=0):
    """Prefix-cache A/B on the Zipf shared-prefix workload: the same request
    stream runs cache-off then cache-on (greedy → token-identical, asserted
    in tests/test_serving_load.py), plus an all-unique control where a 0%
    hit rate must cost nothing. Cache-on prefills only the uncached suffix —
    the ``prefill_tokens_fed`` reduction is the mechanism behind the TTFT /
    throughput win, counted exactly at the feed site."""
    if on_tpu:
        n = n_requests or 48
        shape = dict(n_prefixes=6, prefix_len=384, suffix_lo=16, suffix_hi=96,
                     new_lo=16, new_hi=64)
        budget = 512
    else:
        n = n_requests or 20
        shape = dict(n_prefixes=3, prefix_len=24, suffix_lo=4, suffix_hi=12,
                     new_lo=3, new_hi=8)
        budget = 48

    result = {"config": "prefix_cache_ab", "n_requests": n, "workloads": {}}
    for wl_name, unique in (("zipf_shared", False), ("all_unique", True)):
        wl = make_shared_prefix_workload(n, rate_rps=None, seed=seed, uid_base=0,
                                         unique=unique, **shape)
        line = {}
        for cache_on in (False, True):
            engine = build_engine(on_tpu, prefix_cache=cache_on)
            # warmup compiles the shape buckets so the measured pass times
            # scheduling + (with the cache) skipped prefill, not XLA
            run_splitfuse(engine, [dict(r, uid=r["uid"] + 90_000) for r in wl],
                          token_budget=budget)
            if cache_on:
                engine.prefix_cache.clear()
                engine.prefix_cache.stats.update({k: 0 for k in engine.prefix_cache.stats})
            stats = {}
            done, span = run_splitfuse(engine, wl, token_budget=budget, stats_out=stats)
            key = "cache_on" if cache_on else "cache_off"
            line[key] = {"rps": round(n / span, 2), **_latency_stats(done),
                         "prefill_tokens_fed": stats["prefill_tokens_fed"],
                         "prefill_tokens_skipped": stats["prefill_tokens_skipped"]}
            if cache_on:
                pc = engine.prefix_cache
                line[key]["hit_rate"] = round(pc.hit_rate, 3)
                line[key]["cached_tokens"] = pc.stats["cached_tokens"]
                line[key]["cow_copies"] = pc.stats["cow_copies"]
                line[key]["evictions"] = pc.stats["evictions"]
            line.setdefault("tokens", {})[key] = {u: t for u, (_, t) in sorted(done.items())}
        parity = line["tokens"]["cache_on"] == line["tokens"]["cache_off"]
        del line["tokens"]  # bulky; the bit that matters is the verdict
        line["token_parity"] = parity
        off, on = line["cache_off"], line["cache_on"]
        line["prefill_reduction"] = round(off["prefill_tokens_fed"] /
                                          max(1, on["prefill_tokens_fed"]), 2)
        result["workloads"][wl_name] = line
    return result


def cache_pressure_bench(on_tpu, n_requests=None, seed=0, corpus_mult=4.0):
    """Cache-pressure workload + the MRC estimator's live accuracy check
    (ISSUE 11): a Zipf shared-prefix corpus deliberately sized at
    ``corpus_mult``x the KV block pool, so the radix tree runs under real
    eviction pressure, driven ONE REQUEST AT A TIME (the router_prefix_ab
    discipline: each request's prefix is published before the next looks
    up, so hit accounting measures CACHE behavior, not racing admissions —
    which is also the reference-stream model the estimator assumes).

    Reports the measured full-block hit rate vs the estimator's predicted
    hit rate at 1x capacity (``mrc_abs_err_1x`` is the acceptance metric:
    within 0.05 absolute, asserted in tests/test_cache_telemetry.py), the
    full predicted curve at {0.5x..8x}, the block-lifecycle snapshot
    (block age, eviction-victim age, fragmentation), and the process HBM
    attribution while the engine is live."""
    import jax.numpy as jnp
    from deepspeed_tpu.models import TransformerConfig, TransformerLM
    from deepspeed_tpu.inference.v2 import (CacheTelemetryConfig, DSStateManagerConfig,
                                            DynamicSplitFuseScheduler, InferenceEngineV2,
                                            PrefixCacheConfig, RaggedInferenceEngineConfig)
    from deepspeed_tpu.monitor.memory import hbm_report

    if on_tpu:
        n = n_requests or 128
        cfg = TransformerConfig(vocab_size=32000, hidden_size=1024, num_layers=6,
                                num_heads=8, num_kv_heads=8, intermediate_size=2816,
                                max_seq_len=2048, norm="rmsnorm", positions="rotary",
                                mlp="swiglu", dtype=jnp.bfloat16, attention_impl="flash")
        sm = DSStateManagerConfig(max_tracked_sequences=16, max_ragged_batch_size=512,
                                  max_ragged_sequence_count=16, max_context=768)
        block, pool = 128, 96
        shape = dict(prefix_len=512, suffix_lo=16, suffix_hi=64, new_lo=8, new_hi=32)
        budget = 512
    else:
        n = n_requests or 96
        cfg = TransformerConfig(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                                num_kv_heads=2, intermediate_size=128, max_seq_len=256,
                                dtype=jnp.float32, attention_impl="reference")
        sm = DSStateManagerConfig(max_tracked_sequences=8, max_ragged_batch_size=64,
                                  max_ragged_sequence_count=8, max_context=64)
        block, pool = 8, 48
        shape = dict(prefix_len=40, suffix_lo=4, suffix_hi=10, new_lo=3, new_hi=6)
        budget = 64
    # corpus sized at corpus_mult x the pool: reuse only survives eviction
    # for the Zipf head, exactly the regime the MRC exists to size
    pool_tokens = pool * block
    n_prefixes = max(2, int(round(corpus_mult * pool_tokens / shape["prefix_len"])))
    icfg = RaggedInferenceEngineConfig(
        kv_block_size=block, num_kv_blocks=pool,
        kv_dtype="int8" if on_tpu else jnp.float32, state_manager=sm,
        use_pallas_kernels="auto" if on_tpu else "never",
        prefix_cache=PrefixCacheConfig(
            enabled=True,
            # the CPU smoke trace is a few hundred chunk refs over a 48-block
            # pool — SHARDS sampling noise at that scale swamps the signal,
            # so the smoke tracks every chunk (the sampled path is validated
            # against exact LRU in tests/test_cache_telemetry.py); at TPU
            # scale the trace is long enough for the production sample rate
            telemetry=CacheTelemetryConfig(enabled=True,
                                           mrc_sample_rate=0.25 if on_tpu else 1.0)))
    engine = InferenceEngineV2(TransformerLM(cfg), icfg)
    tel = engine.cache_telemetry
    wl = make_shared_prefix_workload(n, n_prefixes=n_prefixes, rate_rps=None,
                                     seed=seed, uid_base=0, zipf_a=1.2, **shape)
    # warmup compiles the shape buckets on an all-unique stream, then the
    # measured pass starts from a cold, zeroed cache
    warm = make_shared_prefix_workload(max(4, n // 8), n_prefixes=n_prefixes,
                                       rate_rps=None, seed=seed + 7, uid_base=90_000,
                                       unique=True, **shape)
    sched = DynamicSplitFuseScheduler(engine, token_budget=budget)
    for r in warm:
        sched.submit(r["uid"], r["prompt"], max_new_tokens=r["max_new_tokens"])
        sched.run()
    engine.prefix_cache.clear()
    engine.prefix_cache.stats.update({k: 0 for k in engine.prefix_cache.stats})
    tel.reset()

    t0 = time.time()
    for r in wl:  # strictly sequential: publish-before-next-lookup
        sched.submit(r["uid"], r["prompt"], max_new_tokens=r["max_new_tokens"])
        sched.run()
    span = time.time() - t0

    pc = engine.prefix_cache
    snap = tel.snapshot()
    measured = tel.mrc.observed_hit_rate
    predicted_1x = tel.mrc.predict().get(1.0)
    result = {
        "config": "cache_pressure",
        "n_requests": n,
        "corpus_mult": corpus_mult,
        "n_prefixes": n_prefixes,
        "pool_blocks": pool,
        "block_size": block,
        "rps": round(n / span, 2),
        # the live accuracy check: the estimator's 1x prediction vs the real
        # cache's full-block hit rate over the SAME reference stream
        "measured_hit_rate": round(measured, 4) if measured is not None else None,
        "mrc_predicted_1x": round(predicted_1x, 4) if predicted_1x is not None else None,
        "mrc_abs_err_1x": (round(abs(measured - predicted_1x), 4)
                           if measured is not None and predicted_1x is not None else None),
        "mrc": snap["mrc"],
        "request_hit_rate": round(pc.hit_rate, 4),
        "evictions": pc.stats["evictions"],
        "evicted_tokens": pc.stats["evicted_tokens"],
        "cow_copies": pc.stats["cow_copies"],
        "cow_bytes": pc.stats["cow_bytes"],
        "telemetry": snap,
        # HBM attribution while the engine is live: the bench's memory{...}
        "memory": hbm_report(),
    }
    return result


def host_tier_ab(on_tpu, n_requests=None, seed=0, corpus_mult=10.0):
    """Tiered KV-cache A/B (ISSUE 17): the cache_pressure Zipf corpus sized
    at ``corpus_mult``x (~10x) the HBM block pool, run once HBM-only and once
    with the pinned host tier armed, one request at a time. The tier arm's
    eviction victims demote to host instead of dropping, so a re-referenced
    Zipf-head prefix that HBM alone would have lost comes back as a
    promoted hit. Reports the hierarchy hit rate vs the HBM-only hit rate
    (acceptance: strictly above, with greedy token parity), promotion
    latency p50/p99, and TTFT split by how the prefix was served
    (promoted hit vs outright miss) — the user-visible cost of an H2D
    restore vs recomputing the prefill."""
    import jax.numpy as jnp
    from deepspeed_tpu.models import TransformerConfig, TransformerLM
    from deepspeed_tpu.inference.v2 import (CacheTelemetryConfig, DSStateManagerConfig,
                                            DynamicSplitFuseScheduler, HostTierConfig,
                                            InferenceEngineV2, PrefixCacheConfig,
                                            RaggedInferenceEngineConfig)

    if on_tpu:
        n = n_requests or 128
        cfg = TransformerConfig(vocab_size=32000, hidden_size=1024, num_layers=6,
                                num_heads=8, num_kv_heads=8, intermediate_size=2816,
                                max_seq_len=2048, norm="rmsnorm", positions="rotary",
                                mlp="swiglu", dtype=jnp.bfloat16, attention_impl="flash")
        sm = DSStateManagerConfig(max_tracked_sequences=16, max_ragged_batch_size=512,
                                  max_ragged_sequence_count=16, max_context=768)
        # host = 3x pool: hierarchy capacity lands exactly on the MRC's 4.0x
        # multiplier, so the curve's prediction is directly comparable
        block, pool, host_blocks = 128, 96, 288
        shape = dict(prefix_len=512, suffix_lo=16, suffix_hi=64, new_lo=8, new_hi=32)
        budget = 512
    else:
        n = n_requests or 64
        cfg = TransformerConfig(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                                num_kv_heads=2, intermediate_size=128, max_seq_len=256,
                                dtype=jnp.float32, attention_impl="reference")
        sm = DSStateManagerConfig(max_tracked_sequences=8, max_ragged_batch_size=64,
                                  max_ragged_sequence_count=8, max_context=64)
        block, pool, host_blocks = 8, 48, 144  # hierarchy = 4.0x the HBM pool
        shape = dict(prefix_len=40, suffix_lo=4, suffix_hi=10, new_lo=3, new_hi=6)
        budget = 64
    pool_tokens = pool * block
    n_prefixes = max(2, int(round(corpus_mult * pool_tokens / shape["prefix_len"])))
    wl = make_shared_prefix_workload(n, n_prefixes=n_prefixes, rate_rps=None,
                                     seed=seed, uid_base=0, zipf_a=1.2, **shape)
    result = {"config": "host_tier_ab", "n_requests": n, "corpus_mult": corpus_mult,
              "n_prefixes": n_prefixes, "pool_blocks": pool, "block_size": block,
              "host_blocks": host_blocks}
    tokens_by_arm = {}
    for arm, tier_on in (("hbm_only", False), ("host_tier", True)):
        pc_cfg = PrefixCacheConfig(
            enabled=True,
            telemetry=CacheTelemetryConfig(enabled=True,
                                           mrc_sample_rate=0.25 if on_tpu else 1.0),
            host_tier=(HostTierConfig(host_blocks=host_blocks) if tier_on else None))
        icfg = RaggedInferenceEngineConfig(
            kv_block_size=block, num_kv_blocks=pool,
            kv_dtype="int8" if on_tpu else jnp.float32, state_manager=sm,
            use_pallas_kernels="auto" if on_tpu else "never", prefix_cache=pc_cfg)
        engine = InferenceEngineV2(TransformerLM(cfg), icfg)
        sched = DynamicSplitFuseScheduler(engine, token_budget=budget)
        pc = engine.prefix_cache
        # warmup compiles shape buckets on an all-unique stream, then the
        # measured pass starts from a cold cache (cache_pressure discipline)
        warm = make_shared_prefix_workload(max(4, n // 8), n_prefixes=n_prefixes,
                                           rate_rps=None, seed=seed + 7,
                                           uid_base=90_000, unique=True, **shape)
        for r in warm:
            sched.submit(r["uid"], r["prompt"], max_new_tokens=r["max_new_tokens"])
            sched.run()
        pc.clear()
        pc.stats.update({k: 0 for k in pc.stats})
        if engine.cache_telemetry is not None:
            engine.cache_telemetry.reset()

        ttft_by_class = {"promoted_hit": [], "hbm_hit": [], "miss": []}
        t0 = time.time()
        for r in wl:  # strictly sequential: publish-before-next-lookup
            h0, p0 = pc.stats["hits"], pc.stats["promotions"]
            sched.submit(r["uid"], r["prompt"], max_new_tokens=r["max_new_tokens"])
            t_req = time.perf_counter()
            # step until the first generated token lands: TTFT under the
            # same split-fuse budget the throughput arm uses
            while sched.has_work and not sched.new_tokens(r["uid"], 0):
                sched.step()
            ttft_ms = (time.perf_counter() - t_req) * 1e3
            sched.run()
            cls = ("promoted_hit" if pc.stats["promotions"] > p0
                   else "hbm_hit" if pc.stats["hits"] > h0 else "miss")
            ttft_by_class[cls].append(ttft_ms)
        span = time.time() - t0

        line = {"rps": round(n / span, 2),
                "hit_rate": round(pc.hit_rate, 4),
                "cached_tokens": pc.stats["cached_tokens"],
                "evictions": pc.stats["evictions"],
                "requests_by_class": {c: len(v) for c, v in ttft_by_class.items()},
                "ttft_miss_ms": _percentiles(ttft_by_class["miss"]),
                "ttft_hbm_hit_ms": _percentiles(ttft_by_class["hbm_hit"])}
        if tier_on:
            # the headline: what fraction of lookups ANY tier could serve
            line["hierarchy_hit_rate"] = round(pc.hit_rate, 4)
            line["demotions"] = pc.stats["demotions_queued"]
            line["promotions"] = pc.stats["promotions"]
            line["promoted_tokens"] = pc.stats["promoted_tokens"]
            line["ttft_promoted_hit_ms"] = _percentiles(ttft_by_class["promoted_hit"])
            tel = engine.cache_telemetry
            if tel is not None:
                tiers = tel.snapshot().get("tiers", {})
                plat = tiers.get("promote_latency_s") or {}
                line["promote_p50_ms"] = (round(plat["p50"] * 1e3, 3)
                                          if plat.get("p50") is not None else None)
                line["promote_p99_ms"] = (round(plat["p99"] * 1e3, 3)
                                          if plat.get("p99") is not None else None)
                line["host_occupancy_integral_s"] = tiers.get(
                    "host_occupancy_integral_s")
                # the MRC's live accuracy check, one tier up (ISSUE 17
                # acceptance): the curve's prediction at the HIERARCHY's
                # capacity multiplier vs the measured hierarchy (HBM+host)
                # block hit rate over the same reference stream
                mult = (pool + host_blocks) / pool
                pred = tel.mrc.predict().get(mult)
                meas = tel.mrc.observed_hit_rate
                line["mrc_hierarchy_mult"] = mult
                line["mrc_predicted_hierarchy"] = (round(pred, 4)
                                                   if pred is not None else None)
                line["measured_hierarchy_block_hit_rate"] = (
                    round(meas, 4) if meas is not None else None)
                line["mrc_hierarchy_abs_err"] = (
                    round(abs(meas - pred), 4)
                    if meas is not None and pred is not None else None)
            line["tier"] = engine.tiered_store.snapshot()
        else:
            line["hbm_hit_rate"] = round(pc.hit_rate, 4)
        tokens_by_arm[arm] = {u: t for u, t in sorted(sched.results.items())}
        result[arm] = line
        engine.shutdown()
    result["token_parity"] = tokens_by_arm["hbm_only"] == tokens_by_arm["host_tier"]
    result["hit_rate_gain"] = round(result["host_tier"]["hit_rate"]
                                    - result["hbm_only"]["hit_rate"], 4)
    return result


def speculative_ab(on_tpu, n_requests=None, seed=0, k=4, mode="ngram", min_match=None,
                   tree_width=1):
    """Speculative-decoding A/B on the Zipf shared-prefix workload: the same
    request stream runs spec-off then spec-on (greedy → token-identical,
    asserted here and in tests/test_speculative.py). Decode tok/s counts
    GENERATED tokens over the run's wall clock — prefill is identical across
    arms, so the delta is the decode plane. The acceptance rate is the
    lever: each verify forward commits ``accepted + 1`` tokens for one host
    round-trip, so higher acceptance directly multiplies tokens-per-step;
    the tradeoff knob is ``k`` (bigger K amortizes more per accepted run,
    wastes more verify compute when acceptance is low)."""
    from deepspeed_tpu.inference.v2 import SpeculativeConfig

    if on_tpu:
        n = n_requests or 32
        shape = dict(n_prefixes=4, prefix_len=256, suffix_lo=16, suffix_hi=64,
                     new_lo=48, new_hi=96)
        budget = 512
        min_match = 2 if min_match is None else min_match
    else:
        n = n_requests or 12
        shape = dict(n_prefixes=3, prefix_len=24, suffix_lo=4, suffix_hi=10,
                     new_lo=18, new_hi=28)
        budget = 48
        # the CPU smoke model's greedy streams are short and only weakly
        # periodic — a unigram trigger keeps the drafter firing so the A/B
        # measures a real acceptance rate instead of drafting silence
        min_match = 1 if min_match is None else min_match

    wl = make_shared_prefix_workload(n, rate_rps=None, seed=seed, uid_base=0, **shape)
    result = {"config": "speculative_ab", "n_requests": n, "k": k, "mode": mode,
              "min_match": min_match, "tree_width": int(tree_width)}
    tokens = {}
    for spec_on in (False, True):
        spec = SpeculativeConfig(mode=mode, k=k, min_match=min_match,
                                 tree_width=int(tree_width)) if spec_on else None
        engine = build_engine(on_tpu, prefix_cache=True, speculative=spec)
        # warmup compiles every bucket (incl. the verify bucket) so the
        # measured pass times scheduling + speculation, not XLA
        run_splitfuse(engine, [dict(r, uid=r["uid"] + 90_000) for r in wl],
                      token_budget=budget)
        engine.prefix_cache.clear()
        engine.prefix_cache.stats.update({s: 0 for s in engine.prefix_cache.stats})
        stats = {}
        done, span = run_splitfuse(engine, wl, token_budget=budget, stats_out=stats)
        gen_tokens = sum(len(t) for _, t in done.values())
        key = "spec_on" if spec_on else "spec_off"
        result[key] = {"decode_tok_s": round(gen_tokens / span, 1),
                       "rps": round(n / span, 2), **_latency_stats(done)}
        tokens[key] = {u: t for u, (_, t) in sorted(done.items())}
        if spec_on:
            sp = stats.get("spec", {})
            result["accept_rate"] = round(sp.get("accepted", 0) / max(1, sp.get("drafted", 0)), 3)
            result["spec_rounds"] = sp.get("rounds", 0)
            result["drafted_tokens"] = sp.get("drafted", 0)
            result["accepted_tokens"] = sp.get("accepted", 0)
    result["token_parity"] = tokens["spec_on"] == tokens["spec_off"]
    result["decode_tok_s_off"] = result["spec_off"]["decode_tok_s"]
    result["decode_tok_s_on"] = result["spec_on"]["decode_tok_s"]
    result["speedup"] = round(result["decode_tok_s_on"] /
                              max(1e-9, result["decode_tok_s_off"]), 3)
    return result


def speculative_sweep(on_tpu, ks=None, widths=None, modes=("ngram", ), n_requests=None,
                      seed=0):
    """K × tree-width sweep over the Zipf shared-prefix workload with
    per-drafter-mode accept-rate reporting: one shared spec-off baseline,
    then one spec-on arm per (mode, k, width) cell — the grid that answers
    "is the extra verify compute of deeper drafts / wider trees paying for
    itself on THIS traffic". Greedy token parity is asserted in every cell
    (each arm replays the identical request stream)."""
    from deepspeed_tpu.inference.v2 import SpeculativeConfig

    ks = tuple(ks or ((2, 4, 8) if on_tpu else (2, 4)))
    widths = tuple(widths or ((1, 2, 4) if on_tpu else (1, 2)))
    if on_tpu:
        n = n_requests or 16
        shape = dict(n_prefixes=4, prefix_len=256, suffix_lo=16, suffix_hi=64,
                     new_lo=48, new_hi=96)
        budget, min_match = 512, 2
    else:
        n = n_requests or 8
        shape = dict(n_prefixes=3, prefix_len=24, suffix_lo=4, suffix_hi=10,
                     new_lo=14, new_hi=22)
        budget, min_match = 48, 1
    wl = make_shared_prefix_workload(n, rate_rps=None, seed=seed, uid_base=0, **shape)

    def run_arm(spec):
        engine = build_engine(on_tpu, prefix_cache=True, speculative=spec)
        run_splitfuse(engine, [dict(r, uid=r["uid"] + 90_000) for r in wl],
                      token_budget=budget)  # warmup: compile every bucket
        engine.prefix_cache.clear()
        engine.prefix_cache.stats.update({s: 0 for s in engine.prefix_cache.stats})
        stats = {}
        done, span = run_splitfuse(engine, wl, token_budget=budget, stats_out=stats)
        gen = sum(len(t) for _, t in done.values())
        return ({u: t for u, (_, t) in sorted(done.items())},
                round(gen / span, 1), stats.get("spec", {}))

    base_tokens, base_tok_s, _ = run_arm(None)
    grid = []
    for mode in modes:
        for k in ks:
            for w in widths:
                toks, tok_s, sp = run_arm(SpeculativeConfig(
                    mode=mode, k=k, min_match=min_match, tree_width=w))
                grid.append({
                    "mode": mode, "k": int(k), "tree_width": int(w),
                    "accept_rate": round(sp.get("accepted", 0) / max(1, sp.get("drafted", 0)), 3),
                    "drafted": sp.get("drafted", 0), "accepted": sp.get("accepted", 0),
                    "rounds": sp.get("rounds", 0), "backoffs": sp.get("backoffs", 0),
                    "decode_tok_s": tok_s,
                    "speedup": round(tok_s / max(1e-9, base_tok_s), 3),
                    "token_parity": toks == base_tokens,
                })
    by_mode = {m: max((c["accept_rate"] for c in grid if c["mode"] == m), default=0.0)
               for m in modes}
    return {"config": "speculative_sweep", "n_requests": n,
            "decode_tok_s_off": base_tok_s, "grid": grid,
            "best_accept_rate_by_mode": by_mode,
            "all_parity": all(c["token_parity"] for c in grid)}


# ---------------------------------------------------------------------------
# gateway plane: closed-loop HTTP load generation + router A/B
# ---------------------------------------------------------------------------
def _percentiles(vals, keys=(50, 99)):
    if not vals:
        return {f"p{k}_ms": None for k in keys}
    arr = np.asarray(vals)
    return {f"p{k}_ms": round(float(np.percentile(arr, k)), 1) for k in keys}


def _http_generate(host, port, r, stream, timeout_s, slo_class):
    """One ``POST /v1/generate`` with client-side TTFT/TPOT timestamps."""
    import http.client

    body = {"prompt": np.asarray(r["prompt"]).tolist(),
            "max_new_tokens": int(r["max_new_tokens"]), "stream": bool(stream)}
    # a per-row slo_class (mixed-class workloads, e.g. control_ab) beats the
    # call-level default
    cls = r.get("slo_class") or slo_class
    if cls:
        body["slo_class"] = cls
    rec = {"uid": r["uid"], "status": None, "tokens": [], "ttft_ms": None,
           "tpot_ms": None, "latency_ms": None, "error": None,
           "request_id": None, "retry_after": None, "tenant": r.get("tenant"),
           "slo_class": cls}
    t_send = time.time()
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        # a client-supplied id keyed on the workload uid: request-log lines
        # and trace spans join back to the workload row by inspection; a
        # workload row carrying a tenant sends it as X-Tenant-Id (the
        # metering identity)
        headers = {"Content-Type": "application/json",
                   "X-Request-Id": f"load-{r['uid']}"}
        if r.get("tenant"):
            headers["X-Tenant-Id"] = str(r["tenant"])
        conn.request("POST", "/v1/generate", json.dumps(body), headers)
        resp = conn.getresponse()
        rec["status"] = resp.status
        rec["request_id"] = resp.getheader("X-Request-Id")
        rec["retry_after"] = resp.getheader("Retry-After")
        if resp.status != 200:
            payload = json.loads(resp.read() or b"{}")
            rec["error"] = payload.get("error")
            return rec
        if not stream:
            payload = json.loads(resp.read())
            rec["tokens"] = payload["tokens"]
            rec["error"] = payload.get("error")
            rec["ttft_ms"] = payload.get("ttft_ms")  # server-side (no frames)
            rec["tpot_ms"] = payload.get("tpot_ms")
            return rec
        # incremental SSE read: the response closes when the stream ends
        # (HTTP/1.0 semantics), so readline() yields frames as they arrive —
        # client-side token timestamps are the honest TTFT/TPOT
        token_times = []
        ev_lines = []
        while True:
            line = resp.readline()
            if not line:
                break
            line = line.rstrip(b"\r\n")
            if line:
                ev_lines.append(line)
                continue
            if not ev_lines:
                continue
            datas = [ln[5:].lstrip() for ln in ev_lines if ln.startswith(b"data:")]
            ev_lines = []
            if not datas:
                continue
            ev = json.loads(b"\n".join(datas))
            if "token" in ev:
                token_times.append(time.time())
                rec["tokens"].append(ev["token"])
            elif ev.get("done"):
                rec["error"] = ev.get("error")
        if token_times:
            rec["ttft_ms"] = (token_times[0] - t_send) * 1e3
            if len(token_times) > 1:
                rec["tpot_ms"] = ((token_times[-1] - token_times[0])
                                  / (len(token_times) - 1) * 1e3)
        return rec
    except Exception as e:  # noqa: BLE001 — the harness reports, never dies
        rec["error"] = f"{type(e).__name__}: {e}"
        return rec
    finally:
        conn.close()
        rec["latency_ms"] = (time.time() - t_send) * 1e3


def run_http_load(host, port, workload, concurrency=8, stream=True,
                  timeout_s=120.0, slo_class=None):
    """Closed-loop HTTP load over a running gateway: ``concurrency`` workers
    pull arrival-ordered requests, SLEEP until each one's arrival time
    (offered rate honored, not merely timestamped), then drive the request
    to completion before pulling the next. When the pool saturates, later
    requests launch behind schedule — disclosed as ``send_lag_ms_p50`` and
    the offered-vs-achieved gap, which is exactly the honesty the open-loop
    curves lacked. Returns aggregate + per-request records."""
    work = sorted(workload, key=lambda r: r["arrival"])
    records = [None] * len(work)
    cursor = [0]
    lock = threading.Lock()
    t0 = time.time()

    def worker():
        while True:
            with lock:
                i = cursor[0]
                if i >= len(work):
                    return
                cursor[0] += 1
            r = work[i]
            delay = r["arrival"] - (time.time() - t0)
            if delay > 0:
                time.sleep(delay)
            t_send = time.time()
            rec = _http_generate(host, port, r, stream, timeout_s, slo_class)
            rec["send_lag_ms"] = max(0.0, (t_send - t0 - r["arrival"]) * 1e3)
            records[i] = rec

    threads = [threading.Thread(target=worker, daemon=True,
                                name=f"dstpu-loadgen-{i}")
               for i in range(min(concurrency, len(work)))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    makespan = time.time() - t0
    recs = [r for r in records if r is not None]
    done = [r for r in recs if r["status"] == 200 and r["error"] is None]
    shed = [r for r in recs if r["status"] == 429]
    errors = [r for r in recs
              if not (r["status"] == 200 and r["error"] is None) and r["status"] != 429]
    last_arrival = work[-1]["arrival"] if work else 0.0
    agg = {
        "n_requests": len(work),
        "completed": len(done),
        "shed": len(shed),
        "errors": len(errors),
        # offered = what the arrival schedule asked for; achieved = what the
        # system absorbed — divergence means saturation, not a faster clock
        "offered_rps": (round((len(work) - 1) / last_arrival, 2)
                        if last_arrival > 0 else None),
        "achieved_rps": round(len(done) / makespan, 2) if makespan > 0 else None,
        "shed_rate": round(len(shed) / len(work), 3) if work else 0.0,
        "ttft": _percentiles([r["ttft_ms"] for r in done if r["ttft_ms"]]),
        "tpot": _percentiles([r["tpot_ms"] for r in done if r["tpot_ms"]]),
        "latency": _percentiles([r["latency_ms"] for r in done if r["latency_ms"]]),
        "send_lag_ms_p50": (round(float(np.percentile(
            [r["send_lag_ms"] for r in recs], 50)), 1) if recs else None),
    }
    return agg, recs


def build_gateway(n_replicas=2, prefix_cache=True, on_tpu=False, host_blocks=None,
                  **cfg_kwargs):
    """N fresh replicas (identical deterministic params — greedy outputs are
    placement-invariant) under one started gateway."""
    from deepspeed_tpu.serving import GatewayConfig, ServingGateway

    engines = [build_engine(on_tpu, prefix_cache=prefix_cache,
                            host_blocks=host_blocks)
               for _ in range(n_replicas)]
    cfg = GatewayConfig(enabled=True, port=0, **cfg_kwargs)
    return ServingGateway(engines, cfg).start()


def gateway_latency_curves(on_tpu, n_requests=None, seed=0, n_replicas=2):
    """Latency-under-load through the full HTTP plane: a saturated
    calibration pass, then an offered-rate sweep around it — TTFT/TPOT
    p50/p99 + shed rate per point. Engines are the small smoke config
    regardless of backend (two production-sized replicas do not share one
    chip's HBM); the headline serving numbers stay with bench_serving."""
    n = n_requests or (32 if on_tpu else 12)
    shape = dict(prompt_lo=8, prompt_hi=24, new_lo=4, new_hi=10)
    gw = build_gateway(n_replicas=n_replicas, prefix_cache=True)
    # the 2x point must shed, not queue unboundedly: bound the default class
    for cls in gw.config.slo_classes.values():
        cls.max_queue_depth = max(4, n // 2)
    try:
        warm = make_workload(n, rate_rps=None, seed=seed, uid_base=0, **shape)
        run_http_load(gw.config.host, gw.port, warm)  # compile the buckets
        sat = make_workload(n, rate_rps=None, seed=seed, uid_base=10_000, **shape)
        sat_agg, _ = run_http_load(gw.config.host, gw.port, sat)
        result = {"config": "gateway_http_load", "n_requests": n,
                  "n_replicas": n_replicas, "engine_config": "cpu_smoke",
                  "saturated": sat_agg, "curve": []}
        base = sat_agg["achieved_rps"] or 1.0
        for mi, mult in enumerate((0.5, 1.0, 2.0)):
            wl = make_workload(n, rate_rps=base * mult, seed=seed + 1 + mi,
                               uid_base=50_000 + 20_000 * mi, **shape)
            agg, _ = run_http_load(gw.config.host, gw.port, wl)
            result["curve"].append({"offered_mult": mult, **agg})
        return result
    finally:
        gw.stop()


def router_prefix_ab(on_tpu, n_requests=None, seed=0, n_replicas=2, gateway=None):
    """Prefix-aware router vs random placement, same engines, same Zipf
    shared-prefix workload (ISSUE 6 acceptance): the radix-overlap oracle
    keeps each hot prefix on ONE replica, so the fleet pays one cold miss
    per prefix instead of one per (prefix, replica) pair — strictly higher
    AGGREGATE hit rate. Between arms every tree is cleared and its stats
    zeroed; greedy + identical params make the generations
    placement-invariant, reported as ``token_parity``. The load runs with
    ONE closed-loop worker so each request's prefix is published before the
    next routes — hit accounting measures PLACEMENT, not racing admissions
    (both arms, same discipline, so the comparison stays apples-to-apples
    and deterministic under the fixed seeds)."""
    n = n_requests or (48 if on_tpu else 24)
    shape = dict(n_prefixes=4, prefix_len=24, suffix_lo=4, suffix_hi=10,
                 new_lo=3, new_hi=6)
    own = gateway is None
    gw = gateway or build_gateway(n_replicas=n_replicas, prefix_cache=True)
    n_replicas = len(gw.replicas)
    try:
        # compile the shape buckets on an all-unique stream so neither arm
        # pays XLA inside its measured window
        warm = make_shared_prefix_workload(n // 2, rate_rps=None, seed=seed + 7,
                                           uid_base=90_000, unique=True, **shape)
        run_http_load(gw.config.host, gw.port, warm, stream=False)
        out = {"config": "router_prefix_ab", "n_requests": n,
               "n_replicas": n_replicas, "zipf_a": 1.2,
               # cache-hit prefill trims produce chunk shapes the unique-mode
               # warmup never saw, so the FIRST arm pays residual XLA
               # compiles: compare hit rates across arms, not wall-clock
               "note": "arms run sequentially; rps/ttft not arm-comparable",
               "arms": {}}
        tokens = {}
        for ai, policy in enumerate(("random", "prefix")):
            for eng in gw.engines:
                eng.prefix_cache.clear()
                eng.prefix_cache.stats.update({k: 0 for k in eng.prefix_cache.stats})
            gw.router.policy = policy
            wl = make_shared_prefix_workload(n, rate_rps=None, seed=seed,
                                             uid_base=1000 * (ai + 1), **shape)
            agg, recs = run_http_load(gw.config.host, gw.port, wl, stream=False,
                                      concurrency=1)
            hits = sum(e.prefix_cache.stats["hits"] for e in gw.engines)
            lookups = sum(e.prefix_cache.stats["lookups"] for e in gw.engines)
            cached = sum(e.prefix_cache.stats["cached_tokens"] for e in gw.engines)
            out["arms"][policy] = {
                "aggregate_hit_rate": round(hits / lookups, 3) if lookups else 0.0,
                "hits": hits, "lookups": lookups, "cached_tokens": cached,
                "achieved_rps": agg["achieved_rps"],
                "ttft_p50_ms": agg["ttft"]["p50_ms"],
            }
            tokens[policy] = {r["uid"] - 1000 * (ai + 1): list(r["tokens"])
                              for r in recs if r["status"] == 200}
        out["token_parity"] = tokens["random"] == tokens["prefix"]
        out["prefix_beats_random"] = (out["arms"]["prefix"]["aggregate_hit_rate"]
                                      > out["arms"]["random"]["aggregate_hit_rate"])
        return out
    finally:
        if own:
            gw.stop()
        else:  # a borrowed gateway gets its configured policy back
            gw.router.policy = gw.config.router


def multi_tenant_bench(on_tpu, n_requests=None, seed=0, n_replicas=2,
                       n_tenants=4, hot_share=0.4):
    """Multi-tenant closed-loop HTTP load with tenant metering armed (the
    ``bench.py`` ``tenants{...}`` block): N Zipf-share tenants plus one
    adversarial hot tenant, per-tenant CLIENT-side TTFT/TPOT, the meter's
    fairness index, per-tenant prefix hit rates (cached / prompt tokens),
    shed attribution and KV/compute spend — the dashboard that makes a hot
    tenant starving the rest visible BEFORE item 4's quota enforcement
    exists to act on it."""
    from deepspeed_tpu.serving import MeteringConfig

    n = n_requests or (48 if on_tpu else 18)
    gw = build_gateway(n_replicas=n_replicas, prefix_cache=True,
                       metering=MeteringConfig(enabled=True,
                                               top_k=n_tenants + 1))
    try:
        warm = make_multi_tenant_workload(max(6, n // 3), n_tenants=n_tenants,
                                          hot_share=hot_share, seed=seed + 7,
                                          uid_base=90_000)
        run_http_load(gw.config.host, gw.port, warm, stream=False)  # compile buckets
        wl = make_multi_tenant_workload(n, n_tenants=n_tenants, hot_share=hot_share,
                                        seed=seed, uid_base=0)
        agg, recs = run_http_load(gw.config.host, gw.port, wl, stream=False)
        usage = gw.meter.usage_report()
        per_tenant = {}
        ledgers = dict(usage["tenants"])
        by_tenant_recs = {}
        for r in recs:
            by_tenant_recs.setdefault(r.get("tenant"), []).append(r)
        for tenant, led in sorted(ledgers.items()):
            rs = [r for r in by_tenant_recs.get(tenant, ())
                  if r["status"] == 200 and r["error"] is None]
            prompt_tokens = led["uncached_tokens"] + led["cached_tokens"]
            per_tenant[tenant] = {
                "requests": led["requests"], "completed": led["completed"],
                "shed": led["shed"],
                "hit_rate": (round(led["cached_tokens"] / prompt_tokens, 3)
                             if prompt_tokens else 0.0),
                "hit_tokens_cross": led["hit_tokens_cross"],
                "served_tokens": led["served_tokens"],
                "compute_s": led["compute_total_s"],
                "kv_block_s": led["kv_block_s"],
                "queue_s": round(sum(led["queue_s"].values()), 6),
                "ttft": _percentiles([r["ttft_ms"] for r in rs if r["ttft_ms"]]),
                "tpot": _percentiles([r["tpot_ms"] for r in rs if r["tpot_ms"]]),
            }
        hot = per_tenant.get("hot", {})
        rest_ttfts = [r["ttft_ms"] for t, rows in by_tenant_recs.items()
                      if t != "hot" for r in rows
                      if r["status"] == 200 and r["error"] is None and r["ttft_ms"]]
        return {
            "config": "multi_tenant",
            "n_requests": n, "n_tenants": n_tenants, "hot_share": hot_share,
            "n_replicas": n_replicas,
            "achieved_rps": agg["achieved_rps"], "shed_rate": agg["shed_rate"],
            "fairness_index": usage["fairness_index"],
            "starvations": usage["starvations"],
            "tenants_seen": usage["tenants_seen"],
            "hot_tenant_compute_share": (
                round(hot.get("compute_s", 0.0) /
                      max(1e-9, sum(t["compute_s"] for t in per_tenant.values())), 3)
                if per_tenant else None),
            "rest_ttft_p99_ms": (round(float(np.percentile(rest_ttfts, 99)), 1)
                                 if rest_ttfts else None),
            "per_tenant": per_tenant,
        }
    finally:
        gw.stop()


# ---------------------------------------------------------------------------
# request-scoped tracing: log consumption, p99 attribution, overhead A/B
# ---------------------------------------------------------------------------
_STAGES = ("ingress_ms", "queue_ms", "prefill_ms", "decode_ms")


def read_request_log(path):
    """Parse a request-summary JSONL log (rotated siblings ``path.N``
    included, oldest first) into a record list. The rotation chain is
    contiguous (``.1`` is newest rotation), so walk until the first gap —
    no hardcoded bound on how many rotations a config retained."""
    rotated = []
    i = 1
    while os.path.exists(f"{path}.{i}"):
        rotated.append(f"{path}.{i}")
        i += 1
    records = []
    for p in rotated[::-1] + [path]:
        if not os.path.exists(p):
            continue
        with open(p) as f:
            for line in f:
                line = line.strip()
                if line:
                    records.append(json.loads(line))
    return records


def attribution_table(records):
    """The p99-attribution table: where completed requests spent their time
    (per-stage p50/p99), the single p99-TTFT request's own breakdown (the
    forensic 'this one was slow BECAUSE...'), and the fraction of records
    whose stage sum reconstructs end-to-end latency within 10% (the
    honesty check on the breakdown itself)."""
    done = [r for r in records if r.get("finish_reason") in ("length", "eos")]
    out = {"n_records": len(records), "n_completed": len(done),
           "by_reason": {}, "stages_p50_ms": {}, "stages_p99_ms": {},
           "p99_request": None, "breakdown_ok_frac": None, "ttft_p99_ms": None}
    for r in records:
        k = r.get("finish_reason") or "unknown"
        out["by_reason"][k] = out["by_reason"].get(k, 0) + 1
    if not done:
        return out
    for st in _STAGES:
        vals = [r[st] for r in done if r.get(st) is not None]
        if vals:
            out["stages_p50_ms"][st] = round(float(np.percentile(vals, 50)), 2)
            out["stages_p99_ms"][st] = round(float(np.percentile(vals, 99)), 2)
    with_ttft = [r for r in done if r.get("ttft_ms")]
    if with_ttft:
        ttfts = [r["ttft_ms"] for r in with_ttft]
        out["ttft_p99_ms"] = round(float(np.percentile(ttfts, 99)), 2)
        worst = max(with_ttft, key=lambda r: r["ttft_ms"])
        out["p99_request"] = {k: worst.get(k) for k in
                              ("request_id", "slo_class", "route_choice",
                               "prefix_hit_tokens", "prompt_tokens",
                               "ttft_ms", "slo_verdict") + _STAGES}
    ok = 0
    checked = 0
    for r in done:
        parts = [r.get(st) for st in _STAGES]
        if r.get("e2e_ms") and all(p is not None for p in parts):
            checked += 1
            if abs(sum(parts) - r["e2e_ms"]) <= max(0.1 * r["e2e_ms"], 2.0):
                ok += 1
    out["breakdown_ok_frac"] = round(ok / checked, 3) if checked else None
    # migrated/fallback rows (ISSUE 20 satellite): the broker's cost is in
    # the summary records themselves now — surface it alongside the stages
    migrated = [r for r in records if r.get("handoff_state") == "migrated"]
    fallback = [r for r in records if r.get("handoff_state") == "fallback"]
    if migrated or fallback:
        hand = [r["handoff_ms"] for r in migrated + fallback
                if r.get("handoff_ms") is not None]
        waits = [r["resume_wait_ms"] for r in migrated
                 if r.get("resume_wait_ms") is not None]
        out["handoff"] = {
            "migrated": len(migrated), "fallbacks": len(fallback),
            "handoff_ms_p50": (round(float(np.percentile(hand, 50)), 2)
                               if hand else None),
            "handoff_ms_p99": (round(float(np.percentile(hand, 99)), 2)
                               if hand else None),
            "resume_wait_ms_p50": (round(float(np.percentile(waits, 50)), 2)
                                   if waits else None),
            "resume_wait_ms_p99": (round(float(np.percentile(waits, 99)), 2)
                                   if waits else None),
        }
    return out


def tracing_overhead_ab(on_tpu, n_requests=None, seed=0, n_replicas=2):
    """Trace-on vs trace-off A/B over the same closed-loop saturated
    workload: identical engines/config except the ``tracing`` block, so the
    throughput delta IS the tracing tax (the zero-overhead-off claim,
    measured rather than asserted). The trace-on arm also yields the
    p99-attribution table from its request log."""
    from deepspeed_tpu.serving import RequestTraceConfig

    n = n_requests or (32 if on_tpu else 12)
    shape = dict(prompt_lo=8, prompt_hi=24, new_lo=4, new_hi=10)
    out = {"config": "request_tracing_ab", "n_requests": n,
           # arms run sequentially in one process: on CPU smoke the SECOND
           # arm can ride XLA caching the first paid for, so small negative
           # overhead is order noise — judge the tax on TPU steady-state
           "note": "arms sequential; cpu-smoke rps is order-noisy", "arms": {}}
    import shutil

    log_dir = tempfile.mkdtemp(prefix="dstpu_reqlog_")
    log_path = os.path.join(log_dir, "requests.jsonl")
    try:
        for arm in ("trace_off", "trace_on"):
            cfg_kwargs = {}
            if arm == "trace_on":
                cfg_kwargs["tracing"] = RequestTraceConfig(enabled=True,
                                                           log_path=log_path)
            gw = build_gateway(n_replicas=n_replicas, prefix_cache=True,
                               on_tpu=False, **cfg_kwargs)
            try:
                warm = make_workload(n, rate_rps=None, seed=seed, uid_base=0, **shape)
                run_http_load(gw.config.host, gw.port, warm)  # compile buckets
                wl = make_workload(n, rate_rps=None, seed=seed, uid_base=10_000, **shape)
                agg, _ = run_http_load(gw.config.host, gw.port, wl)
                out["arms"][arm] = {"achieved_rps": agg["achieved_rps"],
                                    "completed": agg["completed"],
                                    "ttft_p50_ms": agg["ttft"]["p50_ms"]}
            finally:
                gw.stop()
        off, on = out["arms"]["trace_off"], out["arms"]["trace_on"]
        if off["achieved_rps"] and on["achieved_rps"]:
            out["overhead_pct"] = round(
                (off["achieved_rps"] - on["achieved_rps"]) / off["achieved_rps"] * 100, 2)
        records = read_request_log(log_path)

        def measured(r):  # the warmup pass logged too: keep the 10k-base uids
            rid = str(r.get("request_id", ""))
            return rid.startswith("load-") and rid[5:].isdigit() and int(rid[5:]) >= 10_000

        out["attribution"] = attribution_table([r for r in records if measured(r)])
        return out
    finally:
        shutil.rmtree(log_dir, ignore_errors=True)


def disagg_ab(on_tpu, n_requests=None, seed=0):
    """Disaggregated prefill/decode A/B (ISSUE 18): a decode-heavy
    FOREGROUND stream measured while a BACKGROUND stream of pure long
    prefills (``max_new_tokens=1`` — prefill completes the request) hammers
    the fleet, through the full HTTP plane twice:

      * ``colocated`` — two ``mixed`` replicas; background prefill chunks
        share SplitFuse forwards with foreground decodes on BOTH replicas,
        so every foreground token pays the arbitration (the interference
        PR 7's stage attribution measures);
      * ``disagg``    — ``("prefill", "decode")`` pools; the background
        never leaves the prefill replica, and foreground requests migrate
        their KV to the decode replica through the host-tier handoff and
        decode in prefill-free forwards.

    Both arms arm the host tier (the disagg arm NEEDS it as transport; the
    baseline gets it too so capacity is equal). The headline is foreground
    TPOT p50/p99 — the per-token decode interval the pool split exists to
    protect — plus greedy token parity across arms and the handoff ledger's
    migration stats (p50 latency, fallback rate, volume)."""
    n_fg = n_requests or (24 if on_tpu else 12)
    n_bg = 2 * n_fg
    # foreground: decode-heavy, prompt + new inside the cpu-smoke
    # max_context=64; background: the longest prefill the context takes,
    # one token out (prefill IS the request)
    fg_shape = dict(prompt_lo=16, prompt_hi=28, new_lo=12, new_hi=20)
    bg_shape = dict(prompt_lo=40, prompt_hi=60, new_lo=1, new_hi=1)
    concurrency = 8
    host_blocks = 160
    result = {"config": "disagg_ab", "n_foreground": n_fg, "n_background": n_bg,
              "n_replicas": 2, "engine_config": "cpu_smoke",
              "host_blocks": host_blocks}
    tokens_by_arm = {}
    for arm in ("colocated", "disagg"):
        kwargs = {}
        if arm == "disagg":
            from deepspeed_tpu.serving import DisaggConfig

            kwargs["disagg"] = DisaggConfig(enabled=True,
                                            roles=("prefill", "decode"))
        gw = build_gateway(n_replicas=2, prefix_cache=True,
                           host_blocks=host_blocks, on_tpu=on_tpu, **kwargs)
        try:
            warm = (make_workload(n_fg, rate_rps=None, seed=seed + 7,
                                  uid_base=90_000, **fg_shape)
                    + make_workload(n_bg, rate_rps=None, seed=seed + 8,
                                    uid_base=95_000, **bg_shape))
            run_http_load(gw.config.host, gw.port, warm,
                          concurrency=concurrency)
            # one merged closed-loop run: the background is load, not a
            # separate phase — arrival order interleaves the two streams
            fg = make_workload(n_fg, rate_rps=None, seed=seed, uid_base=0,
                               **fg_shape)
            bg = make_workload(n_bg, rate_rps=None, seed=seed + 1,
                               uid_base=500_000, **bg_shape)
            _agg, recs = run_http_load(gw.config.host, gw.port, fg + bg,
                                       concurrency=concurrency)
            fg_done = [r for r in recs if r["uid"] < 500_000
                       and r["status"] == 200 and r["error"] is None]
            bg_done = [r for r in recs if r["uid"] >= 500_000
                       and r["status"] == 200 and r["error"] is None]
            line = {"fg_completed": len(fg_done), "bg_completed": len(bg_done),
                    "errors": len(recs) - len(fg_done) - len(bg_done),
                    "fg_ttft": _percentiles([r["ttft_ms"] for r in fg_done
                                             if r["ttft_ms"]]),
                    "fg_tpot": _percentiles([r["tpot_ms"] for r in fg_done
                                             if r["tpot_ms"]]),
                    "fg_latency": _percentiles([r["latency_ms"] for r in fg_done
                                                if r["latency_ms"]])}
            if arm == "disagg":
                st = gw.disagg.state()
                line.update({"pools": st["pools"], "migrated": st["migrated"],
                             "fallbacks": st["fallbacks"],
                             "blocks_moved": st["handoff"]["blocks_moved"],
                             "handoff_p50_ms": st["handoff"]["handoff_p50_ms"],
                             "handoff_p99_ms": st["handoff"]["handoff_p99_ms"],
                             "handoff_fallback_rate":
                                 st["handoff"]["handoff_fallback_rate"]})
            tokens_by_arm[arm] = {r["uid"]: list(r["tokens"])
                                  for r in fg_done + bg_done}
            result[arm] = line
        finally:
            gw.stop()
    common = sorted(set(tokens_by_arm["colocated"]) & set(tokens_by_arm["disagg"]))
    result["token_parity"] = bool(common) and all(
        tokens_by_arm["colocated"][u] == tokens_by_arm["disagg"][u]
        for u in common)
    co_p99 = result["colocated"]["fg_tpot"].get("p99_ms")
    dg_p99 = result["disagg"]["fg_tpot"].get("p99_ms")
    result["tpot_p99_improved"] = (co_p99 is not None and dg_p99 is not None
                                   and dg_p99 < co_p99)
    return result


def timeline_rounds(on_tpu, n_requests=None, seed=0, out_dir=None):
    """Two captured timeline rounds for ``tools/trace_explain.py`` (ISSUE
    20): the SAME disagg foreground workload through the full HTTP plane
    twice — once clean (``base``), once with a deterministic 100%-rate
    150 ms chaos stall AT ``serving/handoff`` (``stalled``), which lands
    between the broker's export and verify, so the regression lives inside
    every migrated request's ``broker_verify`` segment. The measured round
    is foreground-only at concurrency 1: sequential requests have no
    queueing neighbors, so the seeded stall's milliseconds land in the
    stalled request's OWN broker segment instead of bleeding into other
    requests' queue/prefill/resume waits (warmup still drives both pools
    with the mixed workload to pin compile buckets). Each arm writes one
    round file (``{"meta": backend stamp, "timelines": [...]}``, measured
    rids only) and the summary runs the differential explain across them:
    the dominant stage must be the stalled broker stage, not a neighbor."""
    from bench import backend_stamp
    from deepspeed_tpu.runtime.resilience.chaos import ChaosSchedule, ChaosSpec
    from deepspeed_tpu.serving import (DisaggConfig, RequestTraceConfig,
                                       TimelineConfig)
    from tools.trace_explain import explain, load_round

    n_fg = n_requests or (16 if on_tpu else 8)
    n_bg = n_fg
    fg_shape = dict(prompt_lo=16, prompt_hi=28, new_lo=12, new_hi=20)
    bg_shape = dict(prompt_lo=40, prompt_hi=60, new_lo=1, new_hi=1)
    out_dir = out_dir or os.path.join(tempfile.gettempdir(),
                                      "dstpu_timeline_rounds")
    os.makedirs(out_dir, exist_ok=True)
    result = {"config": "timeline_rounds", "n_foreground": n_fg,
              "n_background": n_bg, "out_dir": out_dir, "rounds": {}}
    for arm in ("base", "stalled"):
        gw = build_gateway(
            n_replicas=2, prefix_cache=True, host_blocks=160, on_tpu=on_tpu,
            disagg=DisaggConfig(enabled=True, roles=("prefill", "decode")),
            tracing=RequestTraceConfig(enabled=True),
            timeline=TimelineConfig(enabled=True, last_n=1024))
        sched = None
        try:
            warm = (make_workload(n_fg, rate_rps=None, seed=seed + 7,
                                  uid_base=90_000, **fg_shape)
                    + make_workload(n_bg, rate_rps=None, seed=seed + 8,
                                    uid_base=95_000, **bg_shape))
            run_http_load(gw.config.host, gw.port, warm, concurrency=8)
            if arm == "stalled":
                # armed AFTER warmup: the measured rounds differ by exactly
                # the seeded stall, nothing else
                sched = ChaosSchedule(seed + 11, [
                    ChaosSpec("stall", "serving/handoff", rate=1.0,
                              duration_s=0.15)]).install()
            fg = make_workload(n_fg, rate_rps=None, seed=seed, uid_base=0,
                               **fg_shape)
            run_http_load(gw.config.host, gw.port, fg, concurrency=1)
            want = {f"load-{r['uid']}" for r in fg}
            timelines = [t for t in gw.timeline.recent()
                         if t.get("request_id") in want]
            path = os.path.join(out_dir, f"timeline_{arm}.json")
            with open(path, "w") as f:
                json.dump({"meta": {**backend_stamp(on_tpu), "arm": arm},
                           "timelines": timelines}, f, default=repr)
            migrated = [t for t in timelines if t.get("migrated")]
            result["rounds"][arm] = {
                "path": path, "n_timelines": len(timelines),
                "migrated": len(migrated),
                "migrated_coverage_ok_frac":
                    (round(sum(bool(t["coverage_ok"]) for t in migrated)
                           / len(migrated), 3) if migrated else None),
                "chaos_stalls": (sched.counts().get("stall", 0)
                                 if sched is not None else 0),
            }
        finally:
            if sched is not None:
                sched.uninstall()
            gw.stop()
    report = explain(load_round(result["rounds"]["base"]["path"]),
                     load_round(result["rounds"]["stalled"]["path"]))
    result["explain"] = {
        "refused": report["refused"],
        "delta_e2e_ms": report.get("delta_e2e_ms"),
        "dominant_stage": report.get("dominant_stage"),
        "dominant_cause": report.get("dominant_cause"),
        "broker_verify_delta_ms": (report.get("by_stage", {})
                                   .get("broker_verify", {}).get("delta_ms")),
    }
    return result


def control_ab(on_tpu, n_requests=None, seed=0, n_replicas=2):
    """Controller-on vs controller-off A/B (ISSUE 19): the same
    prefill-storm workload — an interactive foreground stream measured
    while a batch stream of long pure prefills floods the queues — through
    the full HTTP plane twice. Identical gateways/SLO classes except the
    ``control`` block, so the delta IS the feedback loop:

      * ``control_off`` — static admission limits; under the storm the
        interactive queue runs deep and TTFT blows through its target;
      * ``control_on``  — the admission policy watches the per-class
        SLO-miss counters and tightens the interactive queue depth live,
        trading shed (429, retryable) for conformance of what it admits.

    The headline is the interactive SLO-miss rate among COMPLETED requests
    (same server-side TTFT-vs-target rule the miss counters use), plus
    greedy token parity over the uids both arms completed, plus the on-arm
    decision ledger (every tighten/relax with its sensor justification).
    The TTFT target itself is calibrated, not hardcoded: 2x the p50 of an
    uncontended interactive pass on this host."""
    from deepspeed_tpu.serving import ControlConfig, SLOClassConfig

    n_fg = n_requests or (24 if on_tpu else 12)
    n_bg = 2 * n_fg
    fg_shape = dict(prompt_lo=8, prompt_hi=16, new_lo=4, new_hi=8)
    bg_shape = dict(prompt_lo=40, prompt_hi=60, new_lo=1, new_hi=1)
    concurrency = 8
    result = {"config": "control_ab", "n_interactive": n_fg, "n_batch": n_bg,
              "n_replicas": n_replicas, "engine_config": "cpu_smoke"}

    # calibration: what does interactive TTFT look like UNCONTENDED on this
    # host? (no slo_class sent — the calibration gateway carries defaults)
    gw = build_gateway(n_replicas=n_replicas, prefix_cache=True, on_tpu=on_tpu)
    try:
        warm = make_workload(n_fg, rate_rps=None, seed=seed + 3,
                             uid_base=700_000, **fg_shape)
        run_http_load(gw.config.host, gw.port, warm, concurrency=2,
                      stream=False)  # compile buckets
        cal = make_workload(n_fg, rate_rps=None, seed=seed + 4,
                            uid_base=710_000, **fg_shape)
        _, cal_recs = run_http_load(gw.config.host, gw.port, cal,
                                    concurrency=2, stream=False)
        ttfts = [r["ttft_ms"] for r in cal_recs
                 if r["status"] == 200 and r["ttft_ms"]]
    finally:
        gw.stop()
    # 3x the uncontended p50 with a generous floor: the target must sit
    # ABOVE the host's prompt-service floor (boundary noise is not a miss)
    # and BELOW the storm's queueing delay (hundreds of ms) — the miss
    # counter should answer "queued behind the storm?", nothing subtler
    target_ms = round(max(3.0 * float(np.percentile(ttfts, 50)), 25.0), 1) \
        if ttfts else 100.0
    result["ttft_target_ms"] = target_ms

    classes = {"interactive": SLOClassConfig(priority=0, max_queue_depth=16,
                                             ttft_target_ms=target_ms),
               "batch": SLOClassConfig(priority=1, max_queue_depth=64)}
    tokens_by_arm = {}
    for arm in ("control_off", "control_on"):
        cfg_kwargs = {"slo_classes": dict(classes)}
        if arm == "control_on":
            cfg_kwargs["control"] = ControlConfig(
                enabled=True, interval_s=0.05, window_s=1.0,
                policies=("admission",), sustain_ticks=2,
                max_actuations_per_window=8, cooldown_s=0.2,
                slo_miss_tighten=0.3, slo_miss_relax=0.05,
                min_queue_depth=1, min_window_completions=3)
        gw = build_gateway(n_replicas=n_replicas, prefix_cache=True,
                           on_tpu=on_tpu, **cfg_kwargs)
        try:
            warm = (make_workload(n_fg, rate_rps=None, seed=seed + 7,
                                  uid_base=900_000, **fg_shape)
                    + make_workload(n_bg, rate_rps=None, seed=seed + 8,
                                    uid_base=950_000, **bg_shape))
            run_http_load(gw.config.host, gw.port, warm,
                          concurrency=concurrency, stream=False)
            fg = make_workload(n_fg, rate_rps=None, seed=seed, uid_base=0,
                               **fg_shape)
            for r in fg:
                r["slo_class"] = "interactive"
            bg = make_workload(n_bg, rate_rps=None, seed=seed + 1,
                               uid_base=500_000, **bg_shape)
            for r in bg:
                r["slo_class"] = "batch"
            _agg, recs = run_http_load(gw.config.host, gw.port, fg + bg,
                                       concurrency=concurrency, stream=False)
            fg_done = [r for r in recs if r["uid"] < 500_000
                       and r["status"] == 200 and r["error"] is None]
            fg_shed = [r for r in recs if r["uid"] < 500_000
                       and r["status"] == 429]
            misses = [r for r in fg_done
                      if r["ttft_ms"] and r["ttft_ms"] > target_ms]
            line = {"fg_completed": len(fg_done), "fg_shed": len(fg_shed),
                    "fg_miss_rate": (round(len(misses) / len(fg_done), 3)
                                     if fg_done else None),
                    "fg_ttft": _percentiles([r["ttft_ms"] for r in fg_done
                                             if r["ttft_ms"]])}
            if arm == "control_on":
                st = gw.controller.state()
                applied = [d for d in gw.controller.decisions.recent()
                           if d["applied"]]
                line.update({
                    "actuations": st["applied"], "deferred": st["deferred"],
                    "ticks": st["ticks"], "errors": st["errors"],
                    "depth_overrides": st["overrides"],
                    "decision_actions": sorted({d["action"] for d in applied}),
                    "decisions_justified": all(d.get("sensors")
                                               for d in applied)})
            tokens_by_arm[arm] = {r["uid"]: list(r["tokens"]) for r in recs
                                  if r["status"] == 200 and r["error"] is None}
            result[arm] = line
        finally:
            gw.stop()
    common = sorted(set(tokens_by_arm["control_off"])
                    & set(tokens_by_arm["control_on"]))
    result["token_parity"] = bool(common) and all(
        tokens_by_arm["control_off"][u] == tokens_by_arm["control_on"][u]
        for u in common)
    off_miss = result["control_off"]["fg_miss_rate"]
    on_miss = result["control_on"]["fg_miss_rate"]
    result["slo_miss_improved"] = (off_miss is not None and on_miss is not None
                                   and on_miss < off_miss)
    return result


def gateway_bench(on_tpu, seed=0):
    """The bench.py serving-block entry: latency-under-load curves + the
    router A/B + the request-tracing attribution/overhead block, one dict."""
    return {"load": gateway_latency_curves(on_tpu, seed=seed),
            "router_ab": router_prefix_ab(on_tpu, seed=seed),
            "tracing": tracing_overhead_ab(on_tpu, seed=seed)}


def main():
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # sitecustomize's config-level jax_platforms beats the env var
        jax.config.update("jax_platforms", "cpu")
    on_tpu = any(d.platform == "tpu" for d in jax.devices())

    # arm the live-health plane for the whole run (serving heartbeats wrap
    # every put/decode): a wedged device forward trips the watchdog instead
    # of the tool hanging silently, and the final JSON reports the counters.
    # DS_TPU_SERVING_HEALTH=0 runs bare; the deadline is generous because a
    # cold compile of a new shape bucket legitimately takes a while.
    health = None
    if os.environ.get("DS_TPU_SERVING_HEALTH", "1") != "0":
        from deepspeed_tpu.monitor.health import get_health

        health = get_health().configure(
            enabled=True,
            deadlines={"serving": float(os.environ.get("DS_TPU_SERVING_DEADLINE_S", "300"))})

    if "shared_prefix" in sys.argv[1:]:
        out = shared_prefix_ab(on_tpu)
    elif "speculative_sweep" in sys.argv[1:]:
        out = speculative_sweep(on_tpu)
    elif "speculative" in sys.argv[1:]:
        out = {"ab": speculative_ab(on_tpu), "sweep": speculative_sweep(on_tpu)}
    elif "gateway" in sys.argv[1:]:
        out = gateway_bench(on_tpu)
    elif "cache_pressure" in sys.argv[1:]:
        out = cache_pressure_bench(on_tpu)
    elif "host_tier" in sys.argv[1:]:
        out = host_tier_ab(on_tpu)
    elif "disagg" in sys.argv[1:]:
        out = disagg_ab(on_tpu)
    elif "control_ab" in sys.argv[1:]:
        out = control_ab(on_tpu)
    elif "timeline" in sys.argv[1:]:
        out = timeline_rounds(on_tpu)
    elif "multi_tenant" in sys.argv[1:]:
        out = multi_tenant_bench(on_tpu)
    else:
        out = serving_load_bench(on_tpu)
    out["on_tpu"] = on_tpu

    if health is not None:
        from deepspeed_tpu.monitor.metrics import get_metrics

        reg = get_metrics()
        out["health"] = {
            "stalls": health.stall_count,
            "stall_serving_total": int(reg.counter("health/stall_serving_total").value),
            "dumps_total": int(reg.counter("health/dumps_total").value),
            "last_dump": health.last_dump_path,
        }
        health.shutdown()
    print(json.dumps(out))


if __name__ == "__main__":
    main()
