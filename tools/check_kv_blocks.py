"""Static check: every KV-block release site in ``inference/v2/`` routes
through the refcount-aware path.

Companion to ``check_timed_ops.py`` / ``check_data_paths.py`` (same lesson:
structural invariants rot silently unless CI asserts them). The prefix-cache
subsystem shares blocks between sequences and the radix tree via per-block
refcounts — a raw ``allocator.free`` / ``kv_cache.free`` call anywhere else
in the serving plane would return a block to the free list while other
holders still reference it, resurrecting exactly the silent free-list
corruption the refcount layer exists to prevent. This AST walk (no package
imports, runs anywhere) asserts that ``.free(...)`` calls appear ONLY inside
the allocator/cache modules themselves; everything else must use
``release`` / ``incref`` / ``flush_sequence``.

A tier-1 test (``tests/test_prefix_cache.py``) runs this on every CI pass.
"""

import ast
import os
import sys

DEFAULT_V2_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir,
                              "deepspeed_tpu", "inference", "v2")

# the only modules allowed to touch the raw free path: the allocator itself,
# the device pool fronting it, the prefix cache (which owns the
# refcount-aware release/evict logic), and the tier store (which owns the
# host pool's free list — the same corruption class, one tier down)
ALLOWED_FILES = (
    os.path.join("ragged", "blocked_allocator.py"),
    os.path.join("ragged", "kv_cache.py"),
    os.path.join("ragged", "prefix_cache.py"),
    os.path.join("ragged", "tiered_store.py"),
)

# call names that bypass the refcount-aware release path: raw HBM frees plus
# the host pool's own mutators — a host_free/host_reserve/host_write outside
# the tier store would detach a block's residency state from the radix tree
RAW_RELEASE_CALLS = ("free", "host_free", "host_reserve", "host_write")


def find_violations(v2_dir=DEFAULT_V2_DIR):
    """[(relpath, lineno, snippet)] for every raw block-free call outside the
    allowlisted allocator/cache modules."""
    violations = []
    for root, _dirs, files in os.walk(v2_dir):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, v2_dir)
            if rel in ALLOWED_FILES:
                continue
            with open(path) as f:
                src = f.read()
            tree = ast.parse(src, filename=path)
            lines = src.splitlines()
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                f_ = node.func
                name = f_.attr if isinstance(f_, ast.Attribute) else (
                    f_.id if isinstance(f_, ast.Name) else None)
                if name in RAW_RELEASE_CALLS:
                    snippet = lines[node.lineno - 1].strip() if node.lineno <= len(lines) else ""
                    violations.append((rel, node.lineno, snippet))
    return violations


def check(v2_dir=DEFAULT_V2_DIR):
    """Return the violation list (empty = every release site is routed)."""
    return find_violations(v2_dir)


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    v2_dir = argv[0] if argv else DEFAULT_V2_DIR
    bad = check(v2_dir)
    if bad:
        print(f"check_kv_blocks: raw block-free calls outside the allocator/cache modules in {v2_dir}:")
        for rel, lineno, snippet in bad:
            print(f"  {rel}:{lineno}: {snippet}")
        return 1
    print("check_kv_blocks: all block-release sites route through the refcount-aware path")
    return 0


if __name__ == "__main__":
    sys.exit(main())
