"""Static check: every public collective in ``deepspeed_tpu/comm/comm.py``
rides ``@timed_op``.

The round-1..5 lesson behind this tool: instrumentation rots silently — the
seed repo wrapped exactly ONE op (``barrier``) and logged ``msg_size=0``, so
all bandwidth accounting was dead for five rounds without any test noticing.
This AST walk (no imports of the package, so it runs anywhere) asserts the
wrap, and a tier-1 test (``tests/test_monitor_trace.py``) runs it on every CI
pass.

Accepted instrumentation forms:

  * ``@timed_op`` (possibly stacked with other decorators) on a ``def``;
  * ``name = timed_op(...)`` assignment (the re-export wrap of the traced
    plane), including nested wrappers like ``timed_op(_eagerize(fn))``;
  * ``name = other`` aliasing where ``other`` is itself instrumented
    (``all_gather_into_tensor = all_gather``).
"""

import ast
import os
import sys

# the public collective surface of deepspeed_tpu.comm (torch.distributed
# signature parity); extend this list when a new collective is exported
PUBLIC_COLLECTIVES = (
    "all_reduce",
    "inference_all_reduce",
    "all_gather",
    "all_gather_into_tensor",
    "reduce_scatter",
    "reduce_scatter_tensor",
    "all_to_all_single",
    "broadcast",
    "ppermute",
    "send_recv_next",
    "send_recv_prev",
    "send",
    "recv",
    "barrier",
    # the explicit ZeRO-3 overlap gather (zero_optimization.overlap_comm)
    # must stay on the same observability surface as the torch-parity ops
    "zero3_params_allgather",
)

DEFAULT_COMM_PY = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir,
                               "deepspeed_tpu", "comm", "comm.py")


def _is_timed_call(node):
    """True for ``timed_op(...)`` with the wrapped target anywhere inside."""
    return (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id == "timed_op")


def find_instrumented(path=DEFAULT_COMM_PY):
    """Names bound (at module level) to a timed_op-wrapped callable."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    instrumented = set()
    aliases = {}  # name -> aliased-to name, resolved after the walk
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if (isinstance(dec, ast.Name) and dec.id == "timed_op") or _is_timed_call(dec):
                    instrumented.add(node.name)
        elif isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if not targets:
                continue
            if _is_timed_call(node.value):
                instrumented.update(targets)
            elif isinstance(node.value, ast.Name):
                for t in targets:
                    aliases[t] = node.value.id
    # resolve alias chains (bounded: an alias cycle terminates the loop)
    for name, target in aliases.items():
        seen = set()
        while target in aliases and target not in seen:
            seen.add(target)
            target = aliases[target]
        if target in instrumented:
            instrumented.add(name)
    return instrumented


def check(path=DEFAULT_COMM_PY, required=PUBLIC_COLLECTIVES):
    """Return the list of public collectives NOT wrapped with @timed_op."""
    instrumented = find_instrumented(path)
    return [name for name in required if name not in instrumented]


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    path = argv[0] if argv else DEFAULT_COMM_PY
    missing = check(path)
    if missing:
        print(f"check_timed_ops: NOT instrumented with @timed_op in {path}: {missing}")
        return 1
    print(f"check_timed_ops: all {len(PUBLIC_COLLECTIVES)} public collectives instrumented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
