"""Pod-scale compile-only validation of the BASELINE.md north-star configs.

The flagship workloads (Llama-2-7B / 70B ZeRO-3 on a v5p-128 pod,
BASELINE.md:21-22) cannot execute in this container — but their full train
steps CAN be traced, GSPMD-partitioned, and memory-checked on a virtual
128-device mesh with nothing materialized (``tpu.abstract_init`` +
``DeepSpeedEngine.aot_lower_train_step``). For each config this prints one
JSON line with:

  - ``lowered``: the full fused train step traced + StableHLO built at the
    target mesh shape (proves the sharding/program construction)
  - ``compiled`` + ``xla_per_device_hbm_gb``: XLA CPU-backend compile of the
    partitioned program and its own per-device memory analysis (argument +
    output + temp + generated code); skipped gracefully if the 7B/70B-scale
    compile exceeds the budget on this host
  - analytic per-chip accounting (independent of XLA): param/optimizer/
    gradient-accumulator shard bytes from the actual state shardings, an
    activation-checkpoint estimate, and the per-step collective volume
    (ZeRO-3 allgather fwd+bwd + reduce-scatter, reference
    ``blogs/zeropp/README.md`` 3M-per-step accounting)
  - ``fits_95gb``: the v5p HBM bound from the analytic estimate

Run: ``python tools/pod_validate.py [--compile] [--devices 128]``
(compile-only is the default ladder; ``--compile`` also runs XLA compiles).
"""

import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

V5P_HBM_GB = 95.0  # v5p: 95 GB HBM per chip
V5P_PEAK_BF16 = 459e12


def _cpu_mesh_env(n):
    env = dict(os.environ)
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (flags + f" --xla_force_host_platform_device_count={n}").strip()
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("JAX_PLATFORM_NAME", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


CONFIGS = [
    # (name, model size, mesh axes, zero stage, micro, gas, seq, extra)
    ("llama2_7b_zero3_dp128", "7b", {"data": 128}, 3, 1, 8, 4096, {}),
    ("llama2_7b_pp8_tp4_dp4", "7b", {"pipe": 8, "model": 4, "data": 4}, 1, 1, 8, 4096, {}),
    ("llama2_7b_ulysses_sp8", "7b", {"data": 16, "seq": 8}, 3, 1, 4, 32768,
     {"sequence_parallel": True, "loss_chunk": 2048}),
    ("llama2_70b_zero3_tp8", "70b", {"data": 16, "model": 8}, 3, 1, 8, 4096, {}),
]


def validate_one(name, size, mesh_axes, stage, micro, gas, seq, extra, do_compile):
    import jax
    import jax.numpy as jnp
    import numpy as np

    jax.config.update("jax_platforms", "cpu")
    from jax._src import xla_bridge

    xla_bridge._clear_backends()

    import deepspeed_tpu
    from deepspeed_tpu.models.llama import llama2_config
    from deepspeed_tpu.models import TransformerLM
    from deepspeed_tpu.parallel import groups
    from deepspeed_tpu.parallel.mesh import DATA_AXIS, DATA_REPL_AXIS, SEQ_AXIS

    groups.reset()
    n_devices = int(np.prod(list(mesh_axes.values())))
    assert len(jax.devices()) >= n_devices, (len(jax.devices()), n_devices)

    cfg = llama2_config(size, max_seq_len=seq, attention_impl="flash", remat=True,
                        remat_policy="save_only_these_names(attn_out)",
                        dtype=jnp.bfloat16, **extra)
    model = TransformerLM(cfg)
    dp = mesh_axes.get("data", 1)
    config = {
        "train_batch_size": micro * gas * dp,
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": stage},
        "bf16": {"enabled": True},
        "steps_per_print": 10**9,
        "tpu": {"mesh": mesh_axes, "abstract_init": True},
    }
    if mesh_axes.get("pipe", 1) > 1:
        config["pipeline"] = {"schedule": "1f1b"}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)

    # ---- analytic per-chip accounting from the ACTUAL state shardings ----
    def shard_frac(leaf):
        spec = getattr(leaf.sharding, "spec", None) or ()
        denom = 1
        for entry in spec:
            for ax in (entry if isinstance(entry, (tuple, list)) else (entry, )):
                if ax is not None:
                    denom *= engine.mesh.shape[ax]
        return denom

    state_bytes = 0
    for leaf in jax.tree_util.tree_leaves(engine.state):
        state_bytes += int(np.prod(leaf.shape)) * leaf.dtype.itemsize // shard_frac(leaf)
    # fp32 gradient accumulator over gas microbatches shards like the params
    grad_acc_bytes = sum(
        int(np.prod(l.shape)) * 4 // shard_frac(l)
        for l in jax.tree_util.tree_leaves(engine.state["params"]))
    # remat(save attn_out): per layer one [B_local, S_local, H] bf16 boundary
    # + attn ctx; times 2 for the layer being recomputed in backward
    sp = mesh_axes.get("seq", 1)
    s_local = seq // sp
    act_bytes = cfg.num_layers * 2 * micro * s_local * cfg.hidden_size * 2 * 2
    logits_bytes = (micro * min(seq, extra.get("loss_chunk", seq)) * cfg.vocab_size * 4
                    // max(1, mesh_axes.get("model", 1)))
    total_gb = (state_bytes + grad_acc_bytes + act_bytes + logits_bytes) / 1e9

    n_params = model.num_params()
    # ZeRO-3 per-step collective volume per chip (reference zeropp blog "3M"):
    # allgather bf16 params fwd + bwd, reduce-scatter fp32->bf16 grads
    if stage == 3:
        coll_gb = 3 * n_params * 2 / 1e9
    elif stage in (1, 2):
        coll_gb = 2 * n_params * 2 / 1e9  # grad reduce + (stage>=1) param refresh
    else:
        coll_gb = n_params * 2 / 1e9

    out = {
        "config": name, "mesh": mesh_axes, "zero": stage, "seq": seq,
        "params_b": round(n_params / 1e9, 2),
        "n_devices": n_devices,
        "analytic_per_chip_gb": round(total_gb, 2),
        "collective_gb_per_step": round(coll_gb, 1),
        "fits_95gb": bool(total_gb < V5P_HBM_GB),
        "lowered": False, "compiled": None, "xla_per_device_hbm_gb": None,
    }

    lowered = engine.aot_lower_train_step(seq)
    out["lowered"] = True
    if do_compile:
        compiled = lowered.compile()
        ma = compiled.memory_analysis()
        if ma is not None and hasattr(ma, "argument_size_in_bytes"):
            per_dev = (ma.argument_size_in_bytes + ma.output_size_in_bytes
                       + ma.temp_size_in_bytes + ma.generated_code_size_in_bytes)
            # CPU-backend analysis reports the per-device partitioned program
            out["xla_per_device_hbm_gb"] = round(per_dev / 1e9, 2)
        out["compiled"] = True
    return out


def main():
    if "--child" in sys.argv:
        i = sys.argv.index("--child")
        name = sys.argv[i + 1]
        do_compile = "--compile" in sys.argv
        spec = next(c for c in CONFIGS if c[0] == name)
        print(json.dumps(validate_one(*spec, do_compile)), flush=True)
        return
    n = int(sys.argv[sys.argv.index("--devices") + 1]) if "--devices" in sys.argv else 128
    do_compile = "--compile" in sys.argv
    results = []
    for spec in CONFIGS:
        cmd = [sys.executable, os.path.abspath(__file__), "--child", spec[0]]
        if do_compile:
            cmd.append("--compile")
        proc = subprocess.run(cmd, env=_cpu_mesh_env(n), cwd=REPO, capture_output=True,
                              text=True, timeout=3600)
        line = next((ln for ln in reversed(proc.stdout.splitlines())
                     if ln.startswith("{")), None)
        if proc.returncode != 0 or line is None:
            results.append({"config": spec[0], "error": proc.stderr[-1500:]})
        else:
            results.append(json.loads(line))
        print(json.dumps(results[-1]), flush=True)
    ok = sum(1 for r in results if r.get("lowered") and r.get("fits_95gb"))
    print(f"POD_VALIDATE SUMMARY: {ok}/{len(CONFIGS)} configs lowered + fit 95GB "
          f"on their target mesh", flush=True)
    if ok < len(CONFIGS):
        sys.exit(1)


if __name__ == "__main__":
    main()
