"""Static check: the checkpoint commit protocol has ONE implementation.

The crash-consistency guarantee (``latest`` only ever references a
manifest-committed tag; superseded tags are deleted only after the newer
commit landed) holds because every pointer flip and every tag deletion goes
through ``deepspeed_tpu/runtime/resilience/saver.py``. A second writer —
an engine "quick fix" that re-grows an inline ``open(latest, 'w')``, a tool
that rmtree's checkpoint dirs — silently reopens the torn-checkpoint window
the subsystem exists to close. This AST walk (no package imports, runs
anywhere) flags:

* any ``open(...)`` call in a writable mode (``w``/``a``/``x``/``+``, or a
  non-literal mode) whose path expression mentions ``LATEST_FILE`` or the
  literal ``"latest"``;
* any ``os.replace`` / ``os.rename`` whose arguments mention the same (the
  tmp+rename idiom is exactly how the real commit path flips the pointer);
* any ``shutil.rmtree`` / ``os.rmdir`` / ``os.removedirs`` call;

outside the allowed commit-path module. A tier-1 test
(``tests/test_resilience.py``) runs it on every CI pass, the same pattern as
``check_timed_ops.py`` / ``check_data_paths.py``.
"""

import ast
import os
import sys

REPO_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
DEFAULT_PKG = os.path.join(REPO_ROOT, "deepspeed_tpu")

# the one module allowed to flip `latest` and delete tags
ALLOWED = ("runtime/resilience/saver.py", )

_WRITE_MODES = ("w", "a", "x", "+")  # '+' upgrades any mode to writable
_RM_CALLS = {("shutil", "rmtree"), ("os", "rmdir"), ("os", "removedirs")}
_RENAME_CALLS = {("os", "replace"), ("os", "rename")}


def _mentions_latest(node):
    """True if the expression subtree references LATEST_FILE or 'latest'."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id == "LATEST_FILE":
            return True
        if isinstance(sub, ast.Attribute) and sub.attr == "LATEST_FILE":
            return True
        if isinstance(sub, ast.Constant) and sub.value == "latest":
            return True
    return False


def _open_mode(call):
    """The literal mode of an open() call, or None when non-literal."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None  # dynamic mode: treat as suspect


def _violations_in(path, rel):
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Name) and func.id == "open":
            mode = _open_mode(node)
            writes = mode is None or any(m in mode for m in _WRITE_MODES)
            if writes and any(_mentions_latest(a) for a in list(node.args) + [kw.value for kw in node.keywords]):
                out.append(f"{rel}:{node.lineno}: 'latest' pointer write outside the "
                           f"resilience commit path")
        elif isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
            if (func.value.id, func.attr) in _RM_CALLS:
                out.append(f"{rel}:{node.lineno}: checkpoint-tag deletion "
                           f"({func.value.id}.{func.attr}) outside the resilience commit path")
            elif ((func.value.id, func.attr) in _RENAME_CALLS
                  and any(_mentions_latest(a) for a in list(node.args) + [kw.value for kw in node.keywords])):
                out.append(f"{rel}:{node.lineno}: 'latest' pointer rename "
                           f"({func.value.id}.{func.attr}) outside the resilience commit path")
    return out


def check(pkg_root=DEFAULT_PKG):
    """Return violations: `latest` writes / tag deletions outside ALLOWED."""
    violations = []
    for root, _dirs, files in os.walk(pkg_root):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            full = os.path.join(root, fname)
            rel = os.path.relpath(full, pkg_root).replace(os.sep, "/")
            if rel in ALLOWED:
                continue
            violations.extend(_violations_in(full, rel))
    return violations


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    pkg = argv[0] if argv else DEFAULT_PKG
    bad = check(pkg)
    if bad:
        print("check_ckpt_commit: commit-protocol violations:")
        for v in bad:
            print(f"  {v}")
        return 1
    print("check_ckpt_commit: all `latest` writes and tag deletions live in the "
          "resilience commit path")
    return 0


if __name__ == "__main__":
    sys.exit(main())
