"""MoE dispatch crossover: one-hot [S,E,C] einsum vs grouped ragged matmul.

VERDICT r4 missing #5 asked for a measured crossover table at E=8 and E=64:
the einsum dispatch materializes capacity-padded [E, C, M] buffers and pays
S*E*C dispatch/combine FLOPs, while the grouped path
(``ops/pallas/grouped_matmul.py``) scales with the routed tokens. One JSON
line per (E, impl) with tokens/s and the measured speedup per E.

Run on a TPU host: ``python tools/moe_crossover.py``. CPU fallback runs tiny
shapes (interpret-mode kernels) so the harness itself stays tested in CI.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bench_impl(impl, S, M, F, E, top_k, dtype, steps, on_tpu):
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.moe.sharded_moe import MOELayer, TopKGate

    gate = TopKGate(M, E, k=top_k)
    layer = MOELayer(gate, M, F, num_local_experts=E, moe_impl=impl)
    params = layer.init(jax.random.PRNGKey(0))
    if dtype != jnp.float32:
        params = jax.tree.map(lambda a: a.astype(dtype), params)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(S, M)), dtype)

    fwd = jax.jit(lambda p, x: layer(p, x, train=False)[0])
    out = fwd(params, x)
    float(np.asarray(out).reshape(-1)[0])  # compile + real barrier
    t0 = time.time()
    for _ in range(steps):
        out = fwd(params, x)
    float(np.asarray(out).reshape(-1)[0])
    dt = (time.time() - t0) / steps
    return S / dt


def main():
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")  # sitecustomize guard
    import jax.numpy as jnp

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    if on_tpu:
        S, M, F, top_k, steps, dtype = 8192, 1024, 4096, 2, 10, jnp.bfloat16
        experts = (8, 64)
    else:
        S, M, F, top_k, steps, dtype = 256, 64, 128, 2, 2, jnp.float32
        experts = (4, 8)

    for E in experts:
        row = {"metric": "moe_dispatch_crossover", "E": E, "S": S, "M": M, "F": F,
               "top_k": top_k, "on_tpu": on_tpu}
        for impl in ("einsum", "grouped"):
            row[f"{impl}_tokens_per_s"] = round(_bench_impl(
                impl, S, M, F, E, top_k, dtype, steps, on_tpu), 1)
        row["grouped_speedup"] = round(row["grouped_tokens_per_s"] /
                                       row["einsum_tokens_per_s"], 3)
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
