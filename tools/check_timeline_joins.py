"""Static check: timeline joinability of the serving emission surface.

The causal timeline plane (``deepspeed_tpu/monitor/timeline.py`` +
``deepspeed_tpu/serving/timeline.py``) joins sensor records to requests by
``request_id``. A span or instant emitted from the handoff/disagg/control
paths WITHOUT one is silently unjoinable: the assembler never sees it, the
critical path quietly loses a stage, and no test fails — exactly the drift
this gate exists to catch (the ``check_request_tracing`` lesson applied to
the join surface).

Scope — the modules whose emissions the assembler joins:
``serving/handoff.py``, ``serving/disagg.py``, ``serving/timeline.py``,
and everything under ``serving/control/``. Checked forms, all AST-only
(no package imports, runs anywhere):

  * ``.instant(...)`` / ``.span(...)`` must pass a ``request_id=`` keyword;
  * ``.complete(...)`` must pass a LITERAL ``args={...}`` dict containing
    a ``"request_id"`` key;
  * ``observe_latency(..., span_args={...})`` must carry ``"request_id"``
    in the literal span_args dict (it forwards to a ``.complete``).

Fleet-scoped emissions with genuinely no request (a ledger-wide gauge
sweep, a controller decision covering the whole fleet) go on the
documented ``NO_REQUEST_ALLOWLIST`` — (file, span-name) -> why — so every
exemption is visible in review instead of silently grandfathered. A tier-1
test (``tests/test_timeline.py``) runs this on every CI pass and asserts
the gate still CATCHES a violation planted in a temp file.
"""

import ast
import os
import sys

_REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
DEFAULT_SERVING_DIR = os.path.join(_REPO, "deepspeed_tpu", "serving")

# files (relative to the serving dir) whose emissions the assembler joins
TARGET_FILES = ("handoff.py", "disagg.py", "timeline.py")
TARGET_SUBDIRS = ("control",)

KEYWORD_EMITTERS = ("instant", "span")
ARGSDICT_EMITTERS = ("complete",)
SPAN_ARGS_EMITTERS = ("observe_latency",)

# (file basename, span/instant name) -> documented reason there is no
# request to join. Keep this SHORT: every row is an emission the timeline
# plane can never attribute.
NO_REQUEST_ALLOWLIST = {
    # a controller decision is fleet-scoped; the record's inflight_rids
    # roster (not the instant) is the sanctioned decision->request join
    ("decisions.py", "control/decision"): "fleet-scoped; joined via inflight_rids",
}


def _call_name(node):
    """Attribute calls -> the attribute name; bare-name calls -> the name
    (observe_latency is imported as a function, not a method)."""
    if not isinstance(node, ast.Call):
        return None
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return None


def _literal_dict_has_request_id(node, kw_name):
    for kw in node.keywords:
        if kw.arg == kw_name and isinstance(kw.value, ast.Dict):
            for key in kw.value.keys:
                if isinstance(key, ast.Constant) and key.value == "request_id":
                    return True
    return False


def _span_name(node):
    """The first positional string constant of the emission (the span /
    instant / latency name) — what the allowlist keys on."""
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    # observe_latency(t0, "name", ...) carries the name second
    if len(node.args) > 1 and isinstance(node.args[1], ast.Constant) \
            and isinstance(node.args[1].value, str):
        return node.args[1].value
    return None


def _allowlisted(fname, node):
    name = _span_name(node)
    return name is not None and (fname, name) in NO_REQUEST_ALLOWLIST


def _check_file(path):
    violations = []
    fname = os.path.basename(path)
    with open(path) as f:
        src = f.read()
    lines = src.splitlines()
    tree = ast.parse(src, filename=path)
    for node in ast.walk(tree):
        name = _call_name(node)
        if name is None:
            continue
        why = None
        if name in KEYWORD_EMITTERS:
            if not any(kw.arg == "request_id" for kw in node.keywords) \
                    and not _allowlisted(fname, node):
                why = (f"'{name}' emission without a request_id= keyword "
                       f"(unjoinable by the timeline assembler)")
        elif name in ARGSDICT_EMITTERS:
            if not _literal_dict_has_request_id(node, "args") \
                    and not _allowlisted(fname, node):
                why = (f"'{name}' emission without a literal "
                       f"args={{'request_id': ...}} entry")
        elif name in SPAN_ARGS_EMITTERS:
            if not _literal_dict_has_request_id(node, "span_args") \
                    and not _allowlisted(fname, node):
                why = (f"'{name}' call without a literal "
                       f"span_args={{'request_id': ...}} entry")
        if why:
            snippet = (lines[node.lineno - 1].strip()
                       if node.lineno <= len(lines) else "")
            violations.append((fname, node.lineno, snippet, why))
    return violations


def _target_paths(serving_dir):
    paths = [os.path.join(serving_dir, f) for f in TARGET_FILES]
    for sub in TARGET_SUBDIRS:
        d = os.path.join(serving_dir, sub)
        if os.path.isdir(d):
            paths.extend(os.path.join(d, f) for f in sorted(os.listdir(d))
                         if f.endswith(".py"))
    return [p for p in paths if os.path.exists(p)]


def find_violations(serving_dir=DEFAULT_SERVING_DIR):
    """[(file, lineno, snippet, why)] across the join surface."""
    violations = []
    for path in _target_paths(serving_dir):
        violations.extend(_check_file(path))
    return violations


def check(serving_dir=DEFAULT_SERVING_DIR):
    """Return the violation list (empty = every emission is joinable)."""
    return find_violations(serving_dir)


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    serving_dir = argv[0] if argv else DEFAULT_SERVING_DIR
    bad = check(serving_dir)
    if bad:
        print(f"check_timeline_joins: unjoinable emissions in {serving_dir}:")
        for rel, lineno, snippet, why in bad:
            print(f"  {rel}:{lineno}: {why}: {snippet}")
        return 1
    print("check_timeline_joins: every handoff/disagg/control emission "
          "carries request_id (or a documented no-request exemption)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
