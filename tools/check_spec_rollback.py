"""Static check: every sequence rewind routes through
``DSStateManager.rollback_to``.

Companion to ``check_kv_blocks.py`` (same lesson: structural invariants rot
silently unless CI asserts them). The speculative-decoding subsystem rewinds
sequences constantly — rejected draft tails, decode-horizon overshoot at
early finish/cancel — and a rewind has FOUR coupled pieces: truncate
``token_history``, rewind ``seen_tokens``, rewind the publish cursor, and
release the tail block references refcount-aware (with a COW duplicate when
the new tail block is still shared). A module mutating any one of those
directly would desynchronize the others: history longer than ``seen_tokens``
poisons radix publishing, a bare ``seen_tokens`` rewind leaks tail blocks,
and a bare tail release under a shared block corrupts other holders' KV.

This AST walk (no package imports, runs anywhere) asserts, over
``inference/v2/`` AND ``serving/``:

  * no assignment / augmented assignment to a ``.seen_tokens`` attribute
    outside the state-manager plane (``ragged/ragged_manager.py``,
    ``ragged/sequence_descriptor.py``);
  * no mutation of ``.token_history`` (slice/``del``/rebind or a mutating
    method call) outside that plane;
  * no direct ``kv_cache.release`` / ``allocator.release`` calls outside
    ``ragged/`` — tail releases belong to ``rollback_to`` / ``flush_sequence``.

A tier-1 test (``tests/test_speculative.py``) runs this on every CI pass.
"""

import ast
import os
import sys

_REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir, "deepspeed_tpu")
DEFAULT_DIRS = (os.path.join(_REPO, "inference", "v2"), os.path.join(_REPO, "serving"))

# the state-manager plane: the only modules allowed to mutate descriptor
# rewind state (rollback_to and the descriptor's own lifecycle methods live
# here; create_sequence_with_prefix seeds seen_tokens/token_history here too)
ALLOWED_REWIND_FILES = (
    os.path.join("ragged", "ragged_manager.py"),
    os.path.join("ragged", "sequence_descriptor.py"),
)

# direct block-release receivers: <x>.kv_cache.release(...) / <x>._allocator
# .release(...) are allowed only inside ragged/ itself
_RELEASE_RECEIVERS = ("kv_cache", "_allocator", "allocator")

_HISTORY_MUTATORS = ("append", "extend", "clear", "pop", "remove", "insert",
                     "sort", "reverse")


def _is_attr(node, name):
    return isinstance(node, ast.Attribute) and node.attr == name


def _check_file(path, rel, allowed_rewinds, allowed_release, violations):
    with open(path) as f:
        src = f.read()
    tree = ast.parse(src, filename=path)
    lines = src.splitlines()

    def flag(node, why):
        snippet = lines[node.lineno - 1].strip() if node.lineno <= len(lines) else ""
        violations.append((rel, node.lineno, why, snippet))

    for node in ast.walk(tree):
        if not allowed_rewinds:
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    if _is_attr(t, "seen_tokens"):
                        flag(node, "direct seen_tokens rewind")
                    if _is_attr(t, "token_history"):
                        flag(node, "token_history rebind")
                    if isinstance(t, ast.Subscript) and _is_attr(t.value, "token_history"):
                        flag(node, "token_history slice assignment")
            if isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) and _is_attr(t.value, "token_history"):
                        flag(node, "token_history del")
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _HISTORY_MUTATORS \
                    and _is_attr(node.func.value, "token_history"):
                flag(node, f"token_history.{node.func.attr}()")
        if not allowed_release:
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "release":
                recv = node.func.value
                recv_name = recv.attr if isinstance(recv, ast.Attribute) else (
                    recv.id if isinstance(recv, ast.Name) else None)
                if recv_name in _RELEASE_RECEIVERS:
                    flag(node, "direct block release (use rollback_to/flush_sequence)")


def find_violations(dirs=DEFAULT_DIRS):
    """[(relpath, lineno, why, snippet)] for every rewind/release site
    outside the state-manager plane."""
    violations = []
    for scan_dir in dirs:
        for root, _dirs, files in os.walk(scan_dir):
            for fname in sorted(files):
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(root, fname)
                rel = os.path.relpath(path, scan_dir)
                allowed_rewinds = rel in ALLOWED_REWIND_FILES
                allowed_release = rel.split(os.sep)[0] == "ragged"
                _check_file(path, rel, allowed_rewinds, allowed_release, violations)
    return violations


def check(dirs=DEFAULT_DIRS):
    """Return the violation list (empty = every rewind routes through
    ``DSStateManager.rollback_to``)."""
    return find_violations(dirs)


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    dirs = tuple(argv) if argv else DEFAULT_DIRS
    bad = check(dirs)
    if bad:
        print("check_spec_rollback: sequence rewinds outside DSStateManager.rollback_to:")
        for rel, lineno, why, snippet in bad:
            print(f"  {rel}:{lineno}: [{why}] {snippet}")
        return 1
    print("check_spec_rollback: all sequence rewinds route through rollback_to")
    return 0


if __name__ == "__main__":
    sys.exit(main())
