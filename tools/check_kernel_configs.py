"""Static check: tuned Pallas kernels consult the kernel-config registry,
never hardcode tile sizes, and keep a reference-oracle fallback.

The tuning pass (``autotuning/kernel_config.py``) only works if every tuned
``pallas_call`` site actually ASKS the registry for its tiles — a hardcoded
``block_q=1024`` default silently pins the kernel to one chip generation and
rots the persisted sweep (the op_builder lesson from the reference: tuned
kernels are a subsystem, not a constant). This AST walk (no package imports,
runs anywhere; tier-1 via ``tests/test_kernel_tuning.py``) enforces, for
every module in ``TUNED_KERNELS``:

  1. each public entrypoint's tile parameters default to ``None`` (the
     registry-resolution sentinel) — an int literal default is the rot;
  2. the module calls ``tuned_tile(...)`` (the one registry API);
  3. the module defines or imports a ``*reference*`` oracle — every tuned
     kernel keeps a numerics fallback/oracle path. (The interpret-mode
     parity tests in ``tests/test_kernel_tuning.py`` & friends prove the
     oracle is real; kernels whose wrappers run eagerly — flash, paged —
     additionally call it as a runtime fallback.)

Drift catch: any OTHER module under ``ops/pallas`` that contains a
``pallas_call`` and gives a tile-named parameter (block_q/block_k/block_n/
q_tile) an int default >= 8 must either join TUNED_KERNELS or the justified
ALLOWLIST below.
"""

import ast
import os
import sys

PALLAS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir,
                          "deepspeed_tpu", "ops", "pallas")

# module -> {entrypoint: [tile params that must default to None]}
TUNED_KERNELS = {
    "flash_attention.py": {"flash_attention": ["block_q", "block_k"]},
    "paged_attention.py": {"paged_attention": ["q_tile", "kv_splits"]},
    "grouped_matmul.py": {"gmm": ["block_k", "block_n"],
                          "tgmm": ["block_k", "block_n"],
                          "grouped_matmul": ["block_k", "block_n"]},
}

# tile-named params the drift catch watches in NEW/untuned kernels
TILE_PARAM_NAMES = {"block_q", "block_k", "block_n", "q_tile", "kv_splits"}

# untuned kernels with hardcoded tiles, each with a reason they are exempt:
ALLOWLIST = {
    # evoformer: AF2 side workload, shapes fixed by the pair representation —
    # not on the serving/training hot path the tuner targets
    "evoformer_attention.py",
    # block-sparse: the BLOCK is the sparsity layout's semantic unit (from the
    # SparsityConfig), not a free performance tile
    "block_sparse_attention.py",
}


def _int_default(node):
    return isinstance(node, ast.Constant) and isinstance(node.value, int) \
        and not isinstance(node.value, bool)


def _arg_defaults(fn: ast.FunctionDef):
    """{param_name: default_node} over positional + kw-only args."""
    out = {}
    pos = fn.args.args
    for arg, dflt in zip(pos[len(pos) - len(fn.args.defaults):], fn.args.defaults):
        out[arg.arg] = dflt
    for arg, dflt in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
        if dflt is not None:
            out[arg.arg] = dflt
    return out


def _module_calls(tree, name_contains):
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            called = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else "")
            if name_contains in called:
                return True
    return False


def _has_reference_oracle(tree):
    """A ``*reference*`` oracle is defined or imported at module level."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and "reference" in node.name:
            return True
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                if "reference" in (alias.asname or alias.name):
                    return True
    return False


def check(pallas_dir=PALLAS_DIR):
    """Return a list of violation strings (empty = clean)."""
    problems = []
    for fname in sorted(os.listdir(pallas_dir)):
        if not fname.endswith(".py") or fname == "__init__.py":
            continue
        path = os.path.join(pallas_dir, fname)
        with open(path) as f:
            src = f.read()
        tree = ast.parse(src, filename=path)
        fns = {n.name: n for n in ast.walk(tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}

        if fname in TUNED_KERNELS:
            for entry, params in TUNED_KERNELS[fname].items():
                fn = fns.get(entry)
                if fn is None:
                    problems.append(f"{fname}: tuned entrypoint {entry}() missing")
                    continue
                defaults = _arg_defaults(fn)
                for p in params:
                    d = defaults.get(p)
                    if d is None and p not in defaults:
                        problems.append(f"{fname}: {entry}() lost its '{p}' tile parameter")
                    elif _int_default(d):
                        problems.append(
                            f"{fname}: {entry}(..., {p}={d.value}) hardcodes a tile size — "
                            f"default must be None (resolved via tuned_tile)")
            if not _module_calls(tree, "tuned_tile"):
                problems.append(f"{fname}: never consults the kernel-config registry "
                                "(no tuned_tile(...) call)")
            if not _has_reference_oracle(tree):
                problems.append(f"{fname}: no reference-oracle fallback (define/import and "
                                "call a '*reference*' implementation)")
        elif fname not in ALLOWLIST and "pallas_call" in src:
            for name, fn in fns.items():
                for p, d in _arg_defaults(fn).items():
                    if p in TILE_PARAM_NAMES and _int_default(d) and d.value >= 8:
                        problems.append(
                            f"{fname}: {name}(..., {p}={d.value}) — new kernel hardcodes a "
                            "tile size; route it through autotuning/kernel_config.tuned_tile "
                            "or add a justified ALLOWLIST entry")
    return problems


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    path = argv[0] if argv else PALLAS_DIR
    problems = check(path)
    if problems:
        print("check_kernel_configs: FAILED")
        for p in problems:
            print("  " + p)
        return 1
    print(f"check_kernel_configs: {len(TUNED_KERNELS)} tuned kernels registry-routed, "
          "reference fallbacks present, no hardcoded tiles")
    return 0


if __name__ == "__main__":
    sys.exit(main())
