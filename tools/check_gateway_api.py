"""Static check: the serving request plane touches ``InferenceEngineV2``
(and everything else) ONLY through public API.

Companion to ``check_kv_blocks.py`` / ``check_data_paths.py`` /
``check_heartbeats.py`` (same lesson: structural invariants rot silently
unless CI asserts them). The gateway/router/admission layer sits ABOVE the
engine: the moment request-plane code reaches into ``state_manager``, the
scheduler's ``_pending``/``_active``, or any ``_private`` engine attribute,
the engine's admission invariants (lifetime KV reservations, refcounted
block sharing, single-writer radix tree) stop being enforceable at one
layer and every future engine refactor silently breaks the gateway. This
AST walk (no package imports, runs anywhere) asserts, for every module in
``deepspeed_tpu/serving/``:

  * no attribute access beginning with ``_`` on anything other than
    ``self``/``cls`` (dunders exempt) — request-plane objects may have
    private state, but may not reach into OTHER objects' private state;
  * no access to the engine-internal surfaces by name:
    ``state_manager`` / ``kv_cache`` / ``allocator`` — the request plane
    budgets through ``available_blocks`` / ``probe_prefix`` /
    ``max_context``, never against raw pool state.

A tier-1 test (``tests/test_gateway.py``) runs this on every CI pass.
"""

import ast
import os
import sys

DEFAULT_SERVING_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir,
                                   "deepspeed_tpu", "serving")

# engine/scheduler internals the request plane must never name, even though
# they are "public" attributes on the engine object itself
FORBIDDEN_ATTRS = ("state_manager", "kv_cache", "allocator")


def _is_self_or_cls(node) -> bool:
    return isinstance(node, ast.Name) and node.id in ("self", "cls")


def find_violations(serving_dir=DEFAULT_SERVING_DIR):
    """[(relpath, lineno, snippet, why)] for every private reach-in or
    named-internal access inside the serving package."""
    violations = []
    for root, _dirs, files in os.walk(serving_dir):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, serving_dir)
            with open(path) as f:
                src = f.read()
            tree = ast.parse(src, filename=path)
            lines = src.splitlines()
            for node in ast.walk(tree):
                if not isinstance(node, ast.Attribute):
                    continue
                attr = node.attr
                why = None
                if attr in FORBIDDEN_ATTRS:
                    why = f"engine internal '{attr}'"
                elif (attr.startswith("_") and not attr.startswith("__")
                        and not _is_self_or_cls(node.value)):
                    why = f"private attribute '{attr}' on a foreign object"
                if why:
                    snippet = lines[node.lineno - 1].strip() if node.lineno <= len(lines) else ""
                    violations.append((rel, node.lineno, snippet, why))
    return violations


def check(serving_dir=DEFAULT_SERVING_DIR):
    """Return the violation list (empty = the request plane is clean)."""
    return find_violations(serving_dir)


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    serving_dir = argv[0] if argv else DEFAULT_SERVING_DIR
    bad = check(serving_dir)
    if bad:
        print(f"check_gateway_api: request-plane code reaches past the public "
              f"engine API in {serving_dir}:")
        for rel, lineno, snippet, why in bad:
            print(f"  {rel}:{lineno}: {why}: {snippet}")
        return 1
    print("check_gateway_api: the serving request plane touches only public API")
    return 0


if __name__ == "__main__":
    sys.exit(main())
