"""Chaos drills: prove the resilience stack under scripted failure storms.

Two arms, both built from production parts only — ``run_resilient`` + the
stall watchdog for training, the serving gateway + closed-loop HTTP load
for serving — with faults driven through the seeded
:class:`~deepspeed_tpu.runtime.resilience.chaos.ChaosSchedule` (never ad-hoc
monkeypatching: the drill exercises exactly the injection points production
code ships with).

**Training arm** (:func:`training_drill`): run N steps undisturbed, then the
same N steps under a kill/stall/straggle/preempt/collective-delay storm with
per-step checkpointing, warm-remesh restarts and the watchdog armed. The
verdicts are the ROADMAP bar:

  * ``loss_parity`` — the per-step loss curve of the stormed run (last
    completed execution of each step) is BIT-IDENTICAL to the undisturbed
    run;
  * ``resumed_tags_valid`` — every disk tag a restart resumed from was
    manifest-valid under DEEP verification (no torn checkpoint was ever
    trusted);
  * ``stall_dumps_match`` — every injected stall produced exactly one
    forensic dump, and each dump names the stalled source;
  * determinism — two drills with the same seed produce the same event log
    (compare :func:`training_drill` ``event_log`` fields).

**Serving arm** (:func:`serving_drill`): closed-loop HTTP load (blocking
mode, so every terminal is an HTTP status) while a chaos kill takes a
replica driver down mid-traffic; the drill restarts it, then exercises a
drain/undrain cycle. Verdicts:

  * ``zero_unreported`` — every request terminated in exactly one of
    {200 + tokens, 429, 503, 504}; nothing hung, nothing vanished;
  * ``retry_after_on_503`` — every 503 carried ``Retry-After``;
  * ``replica_failure_counted`` — the driver death bumped
    ``gateway/replica_failures_total`` (distinct from shed);
  * ``readyz_flipped`` — ``/readyz`` went 503 during drain and recovered.

CLI::

    python tools/chaos_drill.py training --seed 7 --steps 8
    python tools/chaos_drill.py serving  --seed 7 --requests 24
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


# ---------------------------------------------------------------------------
# training arm
# ---------------------------------------------------------------------------
def _train_model():
    import jax.numpy as jnp
    from deepspeed_tpu.models import TransformerConfig, TransformerLM

    return TransformerLM(TransformerConfig(vocab_size=64, hidden_size=16, num_layers=1,
                                           num_heads=2, intermediate_size=32, max_seq_len=16,
                                           dtype=jnp.float32, attention_impl="reference"))


def _train_config(save_every=1, preemption=True):
    return {
        "train_batch_size": 8,
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}},
        "zero_optimization": {"stage": 0},
        "steps_per_print": 10**9,
        "tpu": {"mesh": {"data": 8}},
        # async saves keep the step boundary fast (host snapshot only), so
        # the engine-stall deadline can stay tight without blocking-save
        # wall time tripping it; the preemption final save is still blocking
        "checkpoint": {"save_interval_steps": save_every, "preemption_save": preemption,
                       "remesh_snapshot": True, "async_save": True},
    }


def default_training_storm(seed, stall_duration_s=0.75):
    """The standard kill/stall/straggle/preempt/collective-delay mix. Kills
    and stalls start only after step 1 (a checkpoint exists, the engine
    heartbeat is armed); one preempt exercises the clean-exit + requeue
    path; a saver-stage kill produces a genuinely torn tag the resume scan
    must skip."""
    from deepspeed_tpu.runtime.resilience.chaos import ChaosSchedule, ChaosSpec

    return ChaosSchedule(seed, [
        ChaosSpec("kill", "engine/step", rate=0.22, start_after=1, max_events=2),
        ChaosSpec("stall", "engine/step", rate=0.18, duration_s=stall_duration_s,
                  start_after=1, max_events=2),
        ChaosSpec("straggle", "engine/step", rate=0.30, duration_s=0.02),
        ChaosSpec("preempt", "engine/step", rate=0.10, start_after=2, max_events=1),
        ChaosSpec("collective_delay", "comm/host_collective", rate=0.15,
                  duration_s=0.02, max_events=6),
        ChaosSpec("kill", "after_arrays", rate=0.25, max_events=1),
    ])


def training_drill(seed=0, steps=8, workdir=None, storm=None, deadline_s=0.5,
                   max_requeues=4, max_restarts=16):
    """Run the training chaos drill; returns the verdicts dict (see module
    docstring). ``workdir`` must be a fresh directory (checkpoints + dumps
    land under it); a temp dir is created when absent."""
    import tempfile

    import jax
    import deepspeed_tpu
    from deepspeed_tpu.elasticity import remesh
    from deepspeed_tpu.monitor.health import configure_health, get_health
    from deepspeed_tpu.monitor.metrics import configure_metrics, get_metrics
    from deepspeed_tpu.parallel import groups
    from deepspeed_tpu.runtime.resilience import (TrainingPreempted, is_committed,
                                                  run_resilient)

    workdir = workdir or tempfile.mkdtemp(prefix="chaos_drill_")
    ckpt_dir = os.path.join(workdir, "ckpt")
    dump_dir = os.path.join(workdir, "dumps")
    os.makedirs(ckpt_dir, exist_ok=True)
    os.makedirs(dump_dir, exist_ok=True)

    rng = np.random.default_rng(seed)
    batches = [{"input_ids": rng.integers(0, 64, size=(8, 16), dtype=np.int32)}
               for _ in range(steps)]

    def build_engine():
        groups.reset()
        engine, _, _, _ = deepspeed_tpu.initialize(model=_train_model(),
                                                   config=_train_config())
        return engine

    # -- undisturbed reference run (no storm, no checkpoint dir) ------------
    engine = build_engine()
    want = [float(engine.train_batch(b)) for b in batches]
    engine.destroy()

    # -- stormed run --------------------------------------------------------
    remesh.clear_snapshots()
    configure_metrics(enabled=True)
    # goodput ledger: armed over the stormed run so recovery badput is a
    # MEASURED verdict (restart wall booked as `recovery`, stall sleeps as
    # `stall`, the whole run conserving wall clock), not a log line
    from deepspeed_tpu.monitor.goodput import (configure_goodput, conservation_ok,
                                               get_goodput)

    gp_recovery_before = 0.0
    gp_train = get_goodput().training if get_goodput().enabled else None
    if gp_train is not None:  # plane shared with a caller (bench): delta it
        gp_recovery_before = gp_train.report()["categories"]["recovery"]
    configure_goodput(enabled=True)
    health = configure_health(enabled=True, deadlines={"engine": deadline_s},
                              watchdog_poll_s=0.03, dump_dir=dump_dir,
                              dump_on_destroy=False)
    storm = storm or default_training_storm(seed, stall_duration_s=max(0.6, 3 * deadline_s))
    state = {"losses": {}, "resumes": [], "warm_resumes": 0, "recovery_ms": [],
             "t_down": None, "restarts": 0}

    ds_config = dict(_train_config())
    ds_config["elasticity"] = {"enabled": True, "max_train_batch_size": 8,
                               "micro_batch_sizes": [1], "min_gpus": 1, "max_gpus": 64,
                               "min_time": 0, "version": 0.2}

    def train_fn(batch_config, resume):
        eng = build_engine()
        try:
            eng.set_checkpoint_dir(ckpt_dir)
            tag, _path = resume
            start = 0
            if resume.snapshot is not None:
                remesh.restore_snapshot(eng, resume.snapshot)
                start = eng.global_steps
                state["warm_resumes"] += 1
                state["resumes"].append(("snapshot", resume.snapshot.step))
            elif tag is not None:
                eng.load_checkpoint(ckpt_dir, tag=tag)
                start = eng.global_steps
                state["resumes"].append(("disk", tag))
            for i in range(start, steps):
                loss = float(eng.train_batch(batches[i]))
                # train_batch advanced global_steps to i+1; last write wins —
                # the step's FINAL execution is what the curve compares
                state["losses"][i] = loss
                if state["t_down"] is not None:
                    state["recovery_ms"].append((time.perf_counter() - state["t_down"]) * 1e3)
                    state["t_down"] = None
            # no explicit flush: destroy() below disarms the engine heartbeat
            # FIRST and then joins the writer, so a slow final commit cannot
            # trip a bogus engine-stall dump
            return [state["losses"].get(i) for i in range(steps)]
        except BaseException:
            state["t_down"] = time.perf_counter()
            state["restarts"] += 1
            raise
        finally:
            eng.destroy()

    with storm:
        requeues = 0
        while True:
            out = run_resilient(train_fn, ds_config, save_dir=ckpt_dir,
                                max_restarts=max_restarts, restart_delay_s=0.0,
                                backoff_factor=1.0, deep_verify=True, warm_remesh=True)
            if isinstance(out, TrainingPreempted) and len(state["losses"]) < steps:
                # a preempted job is REQUEUED by the cluster scheduler; the
                # drill plays that role (bounded)
                requeues += 1
                if requeues > max_requeues:
                    break
                continue
            break
    # let any in-flight watchdog pass finish before counting dumps
    time.sleep(0.1)
    health.shutdown()

    # a PREEMPTED step trains + checkpoints but unwinds train_batch before
    # returning its loss (the clean-exit contract), so its loss is
    # unobservable and the resume starts past it — the curve legitimately
    # has a gap there. The bar is: every OBSERVED step bit-identical, the
    # FINAL loss bit-identical (the run converged to the same place), and
    # gaps only where a preempt fired.
    got = [state["losses"].get(i) for i in range(steps)]
    observed = [(g, w) for g, w in zip(got, want) if g is not None]
    n_preempts = storm.counts().get("preempt", 0)
    loss_parity = (got[-1] is not None
                   and all(g == w for g, w in observed)
                   and (steps - len(observed)) <= n_preempts)

    # every disk tag a restart trusted must be deeply manifest-valid
    resumed_disk = [t for kind, t in state["resumes"] if kind == "disk"]
    resumed_tags_valid = all(
        is_committed(os.path.join(ckpt_dir, t), deep=True) for t in resumed_disk)

    # one forensic dump per injected stall, each naming the stalled source
    n_stalls = storm.counts().get("stall", 0)
    dumps = sorted(f for f in os.listdir(dump_dir) if f.startswith("health_stall_"))
    dumps_named = 0
    for f in dumps:
        with open(os.path.join(dump_dir, f)) as fh:
            header = json.loads(fh.readline())
        if "engine" in header.get("reason", ""):
            dumps_named += 1
    stall_dumps_match = (len(dumps) == n_stalls == dumps_named)

    # goodput verdicts: the ledger spans the stormed run's restarts — the
    # kills/preempts above must show up as measured recovery seconds and
    # the category sum must still match wall clock
    gp_rep = None
    gp_conserved = None
    gp_recovery_s = None
    led = get_goodput().training
    if led is not None:
        gp_rep = led.report()
        # the bound makes silent hook-loss a failure: a stormed training
        # run's wall is step-loop time, almost all of it attributable
        gp_conserved = conservation_ok(gp_rep, max_unattributed_frac=0.25)
        gp_recovery_s = round(
            gp_rep["categories"]["recovery"] - gp_recovery_before, 3)

    counts = storm.counts()
    rec = state["recovery_ms"]
    return {
        "arm": "training",
        "seed": seed,
        "steps": steps,
        "loss_parity": bool(loss_parity),
        "resumed_tags_valid": bool(resumed_tags_valid),
        "stall_dumps_match": bool(stall_dumps_match),
        "stall_dumps": len(dumps),
        "events": counts,
        "event_log": storm.event_log(),
        "restarts": state["restarts"],
        "requeues": requeues,
        "warm_resumes": state["warm_resumes"],
        "resumes": state["resumes"],
        "recovery_ms_p50": (round(float(np.percentile(rec, 50)), 1) if rec else None),
        "goodput": gp_rep,
        "goodput_conserved": gp_conserved,
        # recovery badput as a verdict: restarts happened => the ledger
        # measured recovery seconds for them
        "recovery_badput_s": gp_recovery_s,
        "recovery_badput_measured": (state["restarts"] == 0
                                     or (gp_recovery_s or 0.0) > 0),
        "workdir": workdir,
    }


# ---------------------------------------------------------------------------
# serving arm
# ---------------------------------------------------------------------------
def serving_drill(seed=0, n_requests=24, n_replicas=2, kill_after_fires=20,
                  concurrency=4, rate_rps=None, timeout_s=60.0,
                  stall_deadline_s=0.25, dump_dir=None):
    """Run the serving chaos drill; returns the verdicts dict. A chaos kill
    takes one replica driver down under closed-loop blocking HTTP load; the
    drill restarts it once it is observed dead, runs a drain/undrain cycle
    against ``/readyz``, then a stall/straggle storm on the driver loop with
    the serving heartbeat deadline armed — the watchdog must trip on the
    super-deadline stall (and only on it) and the goodput ledger must book
    the wedged interval as ``stalled``, not ``idle``. Recovery badput is a
    measured verdict: the restarted replica's ledger books its down-time as
    ``recovering`` and every replica ledger must conserve wall clock."""
    import tempfile
    import urllib.request
    import urllib.error

    from deepspeed_tpu.monitor.goodput import (configure_goodput, conservation_ok,
                                               get_goodput)
    from deepspeed_tpu.monitor.health import configure_health, get_health
    from deepspeed_tpu.monitor.metrics import configure_metrics, get_metrics
    from deepspeed_tpu.runtime.resilience.chaos import ChaosSchedule, ChaosSpec
    from tools.serving_load import build_gateway, make_workload, run_http_load

    configure_metrics(enabled=True)
    # goodput BEFORE the gateway: replicas attach their serving ledgers at
    # start(). The serving heartbeat DEADLINE is armed later, only under
    # the stall storm — armed during warmup it would trip on every
    # multi-second first compile inside a forward (CPU), marking healthy
    # replicas dead before the drill begins
    configure_goodput(enabled=True)
    dump_dir = dump_dir or tempfile.mkdtemp(prefix="chaos_serving_dumps_")
    health = None
    reg = get_metrics()
    fail_c = reg.counter("gateway/replica_failures_total")
    base_failures = fail_c.value
    gw = build_gateway(n_replicas=n_replicas, prefix_cache=True,
                      request_timeout_s=timeout_s)
    storm = ChaosSchedule(seed, [
        ChaosSpec("kill", "serving/driver", rate=1.0,
                  start_after=kill_after_fires, max_events=1),
    ])

    def readyz():
        try:
            with urllib.request.urlopen(f"{gw.url}/readyz", timeout=5) as r:
                return r.status
        except urllib.error.HTTPError as e:
            return e.code

    result = {"arm": "serving", "seed": seed, "n_requests": n_requests,
              "n_replicas": n_replicas}
    try:
        # warm the compile buckets BEFORE the storm so the kill lands on
        # steady-state decode, not first-compile
        warm = make_workload(4, prompt_lo=8, prompt_hi=16, new_lo=3, new_hi=6,
                             rate_rps=None, seed=seed, uid_base=0)
        run_http_load(gw.config.host, gw.port, warm, concurrency=2, stream=False,
                      timeout_s=timeout_s)

        wl = make_workload(n_requests, prompt_lo=8, prompt_hi=24, new_lo=4, new_hi=10,
                           rate_rps=rate_rps, seed=seed + 1, uid_base=1000)
        load_out = {}

        def load():
            load_out["agg"], load_out["recs"] = run_http_load(
                gw.config.host, gw.port, wl, concurrency=concurrency,
                stream=False, timeout_s=timeout_s)

        storm.install()
        t_load = threading.Thread(target=load, name="chaos-drill-load")
        t_load.start()
        # monitor: restart the replica the storm killed. The loop outlives
        # the load if the kill lands on an idle driver right after the last
        # request — the drill's restart/recovery verdicts still apply
        t_kill = t_recover = None
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            dead = [r for r in gw.replicas if not r.alive]
            if dead and t_kill is None:
                t_kill = time.perf_counter()
            if dead:
                # restart immediately: the dead driver's exit path already
                # drained its queues (fail_for runs in its finally before
                # the thread exits), so there is nothing to wait out — and
                # any artificial pause here would be reported as recovery
                # time the SYSTEM never spent
                for r in dead:
                    r.restart()
                if all(r.alive for r in gw.replicas):
                    t_recover = time.perf_counter()
            if not t_load.is_alive() and (t_recover is not None or not storm.events):
                break
            time.sleep(0.02)
        t_load.join(timeout=timeout_s)
        storm.uninstall()

        recs = load_out.get("recs", [])
        ok_done = [r for r in recs if r["status"] == 200 and not r["error"] and r["tokens"]]
        retryable = [r for r in recs if r["status"] in (429, 503, 504)]
        unreported = [r for r in recs if r not in ok_done and r not in retryable]
        s503 = [r for r in recs if r["status"] == 503]
        result.update({
            "killed": bool(storm.events),
            "kill_observed": t_kill is not None,
            "completed": len(ok_done),
            "n_503": len(s503),
            "n_504": sum(1 for r in recs if r["status"] == 504),
            "n_429": sum(1 for r in recs if r["status"] == 429),
            "zero_unreported": not unreported,
            "unreported": [{"uid": r["uid"], "status": r["status"], "error": r["error"]}
                           for r in unreported],
            "retry_after_on_503": all(r.get("retry_after") for r in s503),
            "replica_failure_counted": fail_c.value > base_failures,
            "recovery_ms": (round((t_recover - t_kill) * 1e3, 1)
                            if t_kill is not None and t_recover is not None else None),
        })

        # drain / undrain: /readyz must flip and recover, and a drained
        # gateway must refuse with a retryable 503
        ready_before = readyz()
        gw.drain(True)
        ready_drained = readyz()
        # a drained gateway must refuse with a RETRYABLE 503 (Retry-After
        # present), not a bare one — this is the deterministic 503 probe,
        # independent of whether the kill above caught requests in a queue
        req = urllib.request.Request(
            f"{gw.url}/v1/generate", method="POST",
            data=json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 2}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=5) as r:
                drained_status, drained_retry = r.status, r.headers.get("Retry-After")
        except urllib.error.HTTPError as e:
            drained_status, drained_retry = e.code, e.headers.get("Retry-After")
        result["drained_503_retry_after"] = (drained_status == 503
                                             and bool(drained_retry))
        gw.drain(False)
        ready_after = readyz()
        result["readyz_flipped"] = (ready_before == 200 and ready_drained == 503
                                    and ready_after == 200)
        # post-recovery traffic completes again on the restarted fleet
        tail = make_workload(4, prompt_lo=8, prompt_hi=16, new_lo=3, new_hi=6,
                             rate_rps=None, seed=seed + 2, uid_base=9000)
        tail_agg, tail_recs = run_http_load(gw.config.host, gw.port, tail,
                                            concurrency=2, stream=False,
                                            timeout_s=timeout_s)
        result["recovered_completions"] = tail_agg["completed"]
        result["recovered"] = tail_agg["completed"] == len(tail_recs)

        # --- stall/straggle storm (ROADMAP 5(b) leftover): the driver loop
        # wedges under load with the serving deadline armed. The super-
        # deadline stall must trip the watchdog (one forensic dump naming
        # the serving source) and the ledger must book the wedged interval
        # as `stalled` — a sub-deadline straggle only skews latency ---
        stall_s = max(0.6, 3 * stall_deadline_s)
        straggle_s = 0.3 * stall_deadline_s
        gp = get_goodput()

        def booked(cat):
            return {r.name: (r._goodput.report()["categories"][cat]
                             if r._goodput is not None else 0.0)
                    for r in gw.replicas}

        stalled_before = booked("stalled")
        # serving deadline armed ONLY under this storm (every bucket is warm
        # by now, so the only super-deadline wedge left is the injected one)
        health = configure_health(enabled=True,
                                  deadlines={"serving": stall_deadline_s},
                                  watchdog_poll_s=0.03, dump_dir=dump_dir,
                                  dump_on_destroy=False)
        stalls_before = health.stall_count
        stall_storm = ChaosSchedule(seed + 10, [
            ChaosSpec("stall", "serving/driver", rate=1.0, duration_s=stall_s,
                      start_after=2, max_events=1),
            ChaosSpec("straggle", "serving/driver", rate=0.5, duration_s=straggle_s,
                      start_after=2, max_events=3),
        ])
        wl_stall = make_workload(max(8, n_requests // 2), prompt_lo=8, prompt_hi=16,
                                 new_lo=3, new_hi=6, rate_rps=None, seed=seed + 3,
                                 uid_base=20_000)
        with stall_storm:
            stall_agg, _ = run_http_load(gw.config.host, gw.port, wl_stall,
                                         concurrency=concurrency, stream=False,
                                         timeout_s=timeout_s)
        time.sleep(2 * 0.03)  # let an in-flight watchdog pass observe
        d_stalled = sum(booked("stalled").values()) - sum(stalled_before.values())
        n_stall_dumps = sum(1 for f in os.listdir(dump_dir)
                            if f.startswith("health_stall_") and "serving" in f)
        n_stalls = stall_storm.counts().get("stall", 0)
        result["stall_storm"] = {
            "events": stall_storm.counts(),
            "completed_under_storm": stall_agg["completed"],
            # the watchdog saw the wedge: one trip per injected stall, each
            # with a forensic dump naming the serving source
            "watchdog_tripped": health.stall_count - stalls_before >= n_stalls > 0,
            "stall_dumps": n_stall_dumps,
            # the ledger's verdict: the wedged seconds are STALLED (within
            # the fire-gap bracket, so >= the injected sleep), never idle
            "stalled_s_booked": round(d_stalled, 3),
            "stalled_not_idle": d_stalled >= 0.8 * stall_s,
        }

        # --- goodput verdicts: recovery badput is measured, and every
        # replica ledger conserves wall clock ---
        reps = {r.name: r._goodput.report() for r in gw.replicas
                if r._goodput is not None}
        # the unattributed bound makes silent hook-loss a failure: a
        # replica's wall is driver-loop time (active/idle/stalled), almost
        # all of it attributable
        result["goodput"] = {
            name: {"wall_s": rep["wall_s"], "categories": rep["categories"],
                   "unattributed_s": rep["unattributed_s"],
                   "conserved": conservation_ok(rep, max_unattributed_frac=0.25)}
            for name, rep in reps.items()}
        result["goodput_conserved"] = bool(reps) and all(
            conservation_ok(rep, max_unattributed_frac=0.25)
            for rep in reps.values())
        # the killed replica's down-time was booked as recovering — a
        # measured number, not a log line
        result["recovery_badput_s"] = round(sum(
            rep["categories"]["recovering"] for rep in reps.values()), 3)
        result["recovery_badput_measured"] = (not result["kill_observed"]
                                              or result["recovery_badput_s"] > 0)
        result["unexpected_compiles"] = gp.sentinel.unexpected("serving")
    finally:
        storm.uninstall()
        gw.stop()
        if health is not None:
            health.shutdown()
    return result


# ---------------------------------------------------------------------------
# control arm: the feedback controller under a kill/stall storm
# ---------------------------------------------------------------------------
def control_drill(seed=0, n_requests=24, n_replicas=2, kill_after_fires=20,
                  concurrency=4, timeout_s=60.0, workdir=None):
    """Chaos-drill the serving control plane: the same kill storm as the
    serving arm, but with an ARMED controller (admission + scaling
    policies, tight tick) making live decisions while replicas die and
    requests queue. Verdicts (the ISSUE 19 bar):

      * ``zero_unreported`` — the controller's actuations never lost a
        request: every terminal is one of {200 + tokens, 429, 503, 504};
      * ``actuations_bounded`` — applied actuations <= the flap budget
        arithmetic (``max_actuations_per_window x ceil(elapsed/window)``,
        one window of margin): the loop provably did not flap;
      * ``decisions_logged`` — the JSONL decision log holds exactly one
        applied record per applied actuation AND the ``control/*``
        counter agrees — no unlogged actuation path exists;
      * ``decisions_justified`` — every applied record carries the
        non-empty sensor readings that justified it.
    """
    import math
    import tempfile

    from deepspeed_tpu.monitor.goodput import configure_goodput
    from deepspeed_tpu.monitor.metrics import configure_metrics, get_metrics
    from deepspeed_tpu.runtime.resilience.chaos import ChaosSchedule, ChaosSpec
    from deepspeed_tpu.serving import ControlConfig, SLOClassConfig
    from tools.serving_load import build_gateway, make_workload, run_http_load

    configure_metrics(enabled=True)
    configure_goodput(enabled=True)
    workdir = workdir or tempfile.mkdtemp(prefix="chaos_control_")
    decision_log = os.path.join(workdir, "decisions.jsonl")
    reg = get_metrics()
    base_actuations = reg.counter("control/actuations_total").value
    ctl = ControlConfig(
        enabled=True, interval_s=0.05, window_s=2.0,
        max_actuations_per_window=4, cooldown_s=0.25, sustain_ticks=2,
        policies=("admission", "scaling"),
        decision_log_path=decision_log, last_n=512,
        # a tight TTFT target on CPU guarantees misses -> the admission
        # policy WILL act during the storm (that is the point of the drill)
        slo_miss_tighten=0.5, slo_miss_relax=0.05,
        min_queue_depth=1, min_window_completions=2,
        queue_depth_undrain=1, idle_frac_drain=0.95)
    classes = {"interactive": SLOClassConfig(priority=0, max_queue_depth=32,
                                             ttft_target_ms=75.0),
               "batch": SLOClassConfig(priority=1, max_queue_depth=32)}
    gw = build_gateway(n_replicas=n_replicas, prefix_cache=True,
                       request_timeout_s=timeout_s, control=ctl,
                       slo_classes=classes)
    storm = ChaosSchedule(seed, [
        ChaosSpec("kill", "serving/driver", rate=1.0,
                  start_after=kill_after_fires, max_events=1),
        ChaosSpec("straggle", "serving/driver", rate=0.3, duration_s=0.02,
                  start_after=2, max_events=6),
    ])
    result = {"arm": "control", "seed": seed, "n_requests": n_requests,
              "n_replicas": n_replicas, "workdir": workdir}
    t_start = time.perf_counter()
    try:
        warm = make_workload(4, prompt_lo=8, prompt_hi=16, new_lo=3, new_hi=6,
                             rate_rps=None, seed=seed, uid_base=0)
        run_http_load(gw.config.host, gw.port, warm, concurrency=2,
                      stream=False, timeout_s=timeout_s)

        wl = make_workload(n_requests, prompt_lo=8, prompt_hi=24, new_lo=4,
                           new_hi=10, rate_rps=None, seed=seed + 1,
                           uid_base=1000)
        load_out = {}

        def load():
            load_out["agg"], load_out["recs"] = run_http_load(
                gw.config.host, gw.port, wl, concurrency=concurrency,
                stream=False, timeout_s=timeout_s)

        storm.install()
        t_load = threading.Thread(target=load, name="chaos-control-load")
        t_load.start()
        # monitor: give the controller's scaling policy first crack at a
        # dead replica (queue pressure un-drains/restarts), then restart
        # any replica still dead after a grace period so the drill never
        # deadlocks on a quiet queue
        t_dead = None
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            dead = [r for r in gw.replicas if not r.alive]
            if dead and t_dead is None:
                t_dead = time.perf_counter()
            if dead and t_dead is not None \
                    and time.perf_counter() - t_dead > 1.0:
                for r in dead:
                    r.restart()
                t_dead = None
            if not t_load.is_alive():
                break
            time.sleep(0.02)
        t_load.join(timeout=timeout_s)
        storm.uninstall()
        elapsed = time.perf_counter() - t_start
        ctl_stats = dict(gw.controller.stats)
        counter_delta = reg.counter("control/actuations_total").value \
            - base_actuations
        ring = gw.controller.decisions.recent()
        result["control_state"] = gw.controller.state()
        gw.stop()  # flushes + closes the decision log

        recs = load_out.get("recs", [])
        ok_done = [r for r in recs
                   if r["status"] == 200 and not r["error"] and r["tokens"]]
        retryable = [r for r in recs if r["status"] in (429, 503, 504)]
        unreported = [r for r in recs
                      if r not in ok_done and r not in retryable]
        decisions = []
        if os.path.exists(decision_log):
            with open(decision_log) as fh:
                decisions = [json.loads(ln) for ln in fh if ln.strip()]
        applied_recs = [d for d in decisions if d.get("applied")]
        applied = ctl_stats["applied"]
        windows = math.ceil(elapsed / ctl.window_s) + 1
        bound = ctl.max_actuations_per_window * windows
        result.update({
            "killed": bool(storm.events),
            "completed": len(ok_done),
            "n_429": sum(1 for r in recs if r["status"] == 429),
            "n_503": sum(1 for r in recs if r["status"] == 503),
            "zero_unreported": not unreported,
            "unreported": [{"uid": r["uid"], "status": r["status"],
                            "error": r["error"]} for r in unreported],
            "elapsed_s": round(elapsed, 2),
            "actuations": applied,
            "deferred": ctl_stats["deferred"],
            "ticks": ctl_stats["ticks"],
            "controller_errors": ctl_stats["errors"],
            "actuation_bound": bound,
            "actuations_bounded": applied <= bound,
            "decisions_logged": (len(applied_recs) == applied
                                 and counter_delta == applied),
            "decisions_justified": all(
                isinstance(d.get("sensors"), dict) and d["sensors"]
                for d in applied_recs),
            "decision_ring": len(ring),
            "decision_actions": sorted({d["action"] for d in applied_recs}),
        })
    finally:
        storm.uninstall()
        if gw.started:
            gw.stop()
    return result


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description="Chaos drills over the resilience stack")
    p.add_argument("arm", choices=("training", "serving", "control"))
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--requests", type=int, default=24)
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--workdir", default=None)
    args = p.parse_args(argv)
    if args.arm == "training":
        out = training_drill(seed=args.seed, steps=args.steps, workdir=args.workdir)
    elif args.arm == "control":
        out = control_drill(seed=args.seed, n_requests=args.requests,
                            n_replicas=args.replicas, workdir=args.workdir)
    else:
        out = serving_drill(seed=args.seed, n_requests=args.requests,
                            n_replicas=args.replicas)
    print(json.dumps(out, indent=2, default=repr))
    return 0


if __name__ == "__main__":
    sys.exit(main())
