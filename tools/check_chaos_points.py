"""Static check: the chaos plane stays a production-safe no-op.

Companion to ``check_timed_ops.py`` / ``check_heartbeats.py`` /
``check_ckpt_commit.py`` (same lesson: structural invariants rot silently
unless CI asserts them). Two rules, both AST-only (no package imports, runs
anywhere):

1. **fire()-only access.** Production modules (everything under
   ``deepspeed_tpu/`` except the implementing package
   ``runtime/resilience/``) may reach :mod:`chaos` / :mod:`fault_injection`
   ONLY through no-op-when-unhooked points: a module-top-level import of
   the module object plus calls to ``fire`` (and the ``armed`` guard, and
   the passive read-side ``observe`` listener registration).
   Conditional imports (inside ``if``/``try``/function bodies) and calls to
   the hook-installing surface (``inject``/``crash_at``/``clear``/
   ``ChaosSchedule``…) are violations — they are how "test-only branches"
   creep into the hot path and how a storm ends up armed in production by
   accident.

2. **No silent swallows.** Every ``except`` handler in ``elasticity/`` and
   ``runtime/resilience/`` must re-raise, raise, or increment a named
   ``health/`` counter (``…counter("health/…").inc()``) — directly or via
   a helper function defined in the same module whose body increments one.
   The resilience plane is the code that runs while everything else is on
   fire; an exception it eats without a number is a forensic dead end.
"""

import ast
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_PKG = os.path.join(_HERE, os.pardir, "deepspeed_tpu")

CHAOS_MODULES = {"chaos", "fault_injection"}
# the only attributes production code may touch on the chaos module object.
# `observe` is read-side: a passive listener registration that never
# installs hooks and is a no-op while nothing fires (the timeline plane's
# chaos-fire join source) — unlike inject/crash_at/ChaosSchedule it cannot
# arm a fault in production.
ALLOWED_ATTRS = {"fire", "armed", "observe"}
EXCEPT_DIRS = (
    os.path.join(_PKG, "elasticity"),
    os.path.join(_PKG, "runtime", "resilience"),
)
# the implementing package: exempt from rule 1 (it IS the registry) but
# covered by rule 2
_IMPL_DIR = os.path.join(_PKG, "runtime", "resilience")


def _iter_py_files(target):
    if os.path.isfile(target):
        yield target
        return
    for root, _dirs, files in os.walk(target):
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(root, f)


def _rel(path):
    return os.path.relpath(path, os.path.join(_HERE, os.pardir))


# ---------------------------------------------------------------------------
# rule 1: fire()-only access from production modules
# ---------------------------------------------------------------------------
def _chaos_import_aliases(tree, violations, path):
    """Names that refer to a chaos module in this file; flags conditional
    imports (any import of chaos that is not a direct module-body child)."""
    aliases = set()
    module_body = set(map(id, tree.body))

    for node in ast.walk(tree):
        names = []
        if isinstance(node, ast.Import):
            names = [(a.name.rsplit(".", 1)[-1], a.asname or a.name.split(".")[0])
                     for a in node.names]
        elif isinstance(node, ast.ImportFrom):
            mod_leaf = (node.module or "").rsplit(".", 1)[-1]
            for a in node.names:
                if a.name in CHAOS_MODULES:
                    names.append((a.name, a.asname or a.name))
                elif mod_leaf in CHAOS_MODULES:
                    # `from ...chaos import X`: importing members directly —
                    # only `fire`/`armed` are acceptable points
                    if a.name not in ALLOWED_ATTRS:
                        violations.append(
                            f"{_rel(path)}:{node.lineno} imports {a.name!r} from the "
                            f"chaos plane — production modules may only use "
                            f"{sorted(ALLOWED_ATTRS)} (hook installation is test/"
                            f"drill-only API)")
                    names.append((a.name, a.asname or a.name))
        if not names:
            continue
        chaos_names = [(leaf, bound) for leaf, bound in names if leaf in CHAOS_MODULES
                       or leaf in ALLOWED_ATTRS]
        if not chaos_names:
            continue
        if id(node) not in module_body:
            violations.append(
                f"{_rel(path)}:{node.lineno} conditional/nested import of the chaos "
                f"plane — chaos must be imported at module top level so fire() "
                f"points are unconditionally present (no test-only branches)")
        for leaf, bound in chaos_names:
            if leaf in CHAOS_MODULES:
                aliases.add(bound)
    return aliases


def _check_fire_only(path, tree, violations):
    aliases = _chaos_import_aliases(tree, violations, path)
    if not aliases:
        return
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
                and node.value.id in aliases:
            if node.attr not in ALLOWED_ATTRS:
                violations.append(
                    f"{_rel(path)}:{node.lineno} production access to chaos plane "
                    f"attribute {node.attr!r} — only {sorted(ALLOWED_ATTRS)} are "
                    f"no-op-when-unhooked; hook installation belongs in tests/"
                    f"drills")


# ---------------------------------------------------------------------------
# rule 2: no silent swallows in elasticity/ + runtime/resilience/
# ---------------------------------------------------------------------------
def _is_health_counter_inc(node):
    """Matches ``<anything>.counter("health/…")….inc(…)``."""
    if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr == "inc"):
        return False
    target = node.func.value
    # unwrap chained attributes between counter() and inc() (there are none
    # today, but `.labels(...)`-style chains are the obvious future shape)
    while isinstance(target, ast.Attribute):
        target = target.value
    if not (isinstance(target, ast.Call) and isinstance(target.func, (ast.Attribute, ast.Name))):
        return False
    fname = target.func.attr if isinstance(target.func, ast.Attribute) else target.func.id
    if fname != "counter" or not target.args:
        return False
    arg = target.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value.startswith("health/")
    if isinstance(arg, ast.JoinedStr) and arg.values:
        head = arg.values[0]
        return (isinstance(head, ast.Constant) and isinstance(head.value, str)
                and head.value.startswith("health/"))
    return False


def _body_has_escape(body_nodes, helper_ok):
    """True when the statement list contains a raise, a health-counter
    increment, or a call to a known counting helper."""
    for stmt in body_nodes:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Raise):
                return True
            if _is_health_counter_inc(sub):
                return True
            if isinstance(sub, ast.Call):
                f = sub.func
                fname = f.attr if isinstance(f, ast.Attribute) else \
                    (f.id if isinstance(f, ast.Name) else None)
                if fname in helper_ok:
                    return True
    return False


def _counting_helpers(tree):
    """Module functions whose body raises or increments a health/ counter —
    one level of resolution for handlers that delegate (``_record_failure``)."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(node):
                if _is_health_counter_inc(sub):
                    out.add(node.name)
                    break
    return out


def _check_excepts(path, tree, violations):
    helpers = _counting_helpers(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if _body_has_escape(node.body, helpers):
            continue
        what = ast.unparse(node.type) if node.type is not None else "<bare>"
        violations.append(
            f"{_rel(path)}:{node.lineno} `except {what}` neither re-raises nor "
            f"increments a named health/ counter — a silent swallow in the "
            f"resilience plane is a forensic dead end")


# ---------------------------------------------------------------------------
def check(pkg_dir=None, except_dirs=None):
    """Return a list of human-readable violations (empty == clean)."""
    pkg_dir = pkg_dir or _PKG
    impl = os.path.abspath(_IMPL_DIR) if pkg_dir == _PKG else \
        os.path.join(os.path.abspath(pkg_dir), "runtime", "resilience")
    violations = []
    for path in _iter_py_files(pkg_dir):
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        if not os.path.abspath(path).startswith(impl):
            _check_fire_only(path, tree, violations)
    for target in (except_dirs if except_dirs is not None
                   else (EXCEPT_DIRS if pkg_dir == _PKG else
                         [os.path.join(pkg_dir, "elasticity"),
                          os.path.join(pkg_dir, "runtime", "resilience")])):
        for path in _iter_py_files(target):
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
            _check_excepts(path, tree, violations)
    return violations


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    violations = check(argv[0] if argv else None)
    if violations:
        print("check_chaos_points: FAILED")
        for v in violations:
            print(f"  - {v}")
        return 1
    print("check_chaos_points: chaos plane is fire()-only and the resilience "
          "plane swallows nothing silently")
    return 0


if __name__ == "__main__":
    sys.exit(main())
