"""Serving-decode roofline breakdown.

VERDICT r3 weak #3: decode ran at 0.59x the HBM roofline with no analysis of
where the other 41% went. This harness separates the three suspects and
prints one JSON line per measurement so the gap is attributable, not vibes:

  1. ``kernel``   — the paged-attention Pallas kernel alone (same shapes the
     bench's steady-state decode uses): device time per step vs the KV bytes
     it must stream. Gap here = kernel occupancy problem.
  2. ``layer``    — one full decode layer stack step via the compiled ragged
     forward (weights + KV): adds the weight stream and the qkv/mlp gemms.
     Gap vs (1) = weight-stream / fusion problem.
  3. ``horizon``  — engine.decode at horizons 8..128: per-token time should
     fall as 1/horizon toward the device floor; the flat remainder is host
     dispatch (the axon relay pays ~50ms per call). Gap here = host loop.

Run on a TPU host: ``python tools/decode_profile.py`` (add ``--kv int8`` for
the quantized cache). CPU fallback runs tiny shapes so the harness itself
stays tested in CI.

The roofline itself comes from the shared plane (``monitor/roofline.py``):
the peak-bandwidth denominator is the ``CHIP_PEAK_HBM_BW`` table (one table
for the whole repo — this tool and the plane can never disagree about the
roof), and each measurement's bytes numerator is XLA's own
``cost_analysis()`` out of the executable-cost registry, with the old
analytic KV-bytes estimate printed alongside as disclosure.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _sync(x):
    return float(np.asarray(x).reshape(-1)[0])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--kv", choices=["bf16", "int8"], default="bf16")
    ap.add_argument("--seqs", type=int, default=32)
    ap.add_argument("--ctx", type=int, default=640)
    args = ap.parse_args()

    import os

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the sitecustomize's config-level jax_platforms beats the env var;
        # honor an explicit CPU pin instead of touching the (possibly hung)
        # TPU tunnel (same guard as bench.py / autotuning/trial.py)
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    from deepspeed_tpu.models import TransformerConfig, TransformerLM
    from deepspeed_tpu.inference.v2 import InferenceEngineV2, RaggedInferenceEngineConfig

    if on_tpu:
        cfg = TransformerConfig(vocab_size=32000, hidden_size=2048, num_layers=12,
                                num_heads=16, num_kv_heads=16, intermediate_size=5632,
                                max_seq_len=2048, dtype=jnp.bfloat16, attention_impl="flash")
        n_seqs, ctx, bs, reps = args.seqs, args.ctx, 128, 20
    else:
        cfg = TransformerConfig(vocab_size=512, hidden_size=128, num_layers=2, num_heads=8,
                                num_kv_heads=8, intermediate_size=256, max_seq_len=512,
                                dtype=jnp.float32, attention_impl="reference")
        n_seqs, ctx, bs, reps = 4, 128, 64, 2

    # shared peak tables + cost registry (monitor/roofline.py): the SAME
    # roofline the serving plane verdicts against. Unknown chip (CPU CI):
    # an explicit assumed bandwidth roof, disclosed — never a silent guess.
    from deepspeed_tpu.monitor.roofline import configure_roofline

    rf = configure_roofline(enabled=True)
    hbm_bw = rf.peaks()[1]
    assumed_roof = hbm_bw is None
    if assumed_roof:
        rf.configure(peak_hbm_bw=50e9)
        hbm_bw = 50e9

    nkv, d, L = cfg.num_kv_heads, cfg.head_dim, cfg.num_layers
    kv_int8 = args.kv == "int8"
    kv_dtype = jnp.int8 if kv_int8 else cfg.dtype
    kv_itemsize = 1 if kv_int8 else np.dtype(np.float16).itemsize

    # ---- 1. kernel-only: one layer's paged attention at decode shapes ----
    from deepspeed_tpu.ops.pallas.paged_attention import paged_attention

    NB_per_seq = -(-ctx // bs)
    NB = n_seqs * NB_per_seq + 1
    pool_len = NB * bs
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(n_seqs, cfg.num_heads, d)), cfg.dtype)
    k_pool = jnp.asarray(rng.normal(size=(pool_len, nkv, d)), jnp.float32).astype(kv_dtype)
    v_pool = jnp.asarray(rng.normal(size=(pool_len, nkv, d)), jnp.float32).astype(kv_dtype)
    scales = {}
    if kv_int8:
        scales = {"k_scale": jnp.ones((nkv, pool_len), jnp.float32),
                  "v_scale": jnp.ones((nkv, pool_len), jnp.float32)}
    tables = jnp.asarray(np.arange(n_seqs * NB_per_seq).reshape(n_seqs, NB_per_seq), jnp.int32)
    seq_idx = jnp.arange(n_seqs, dtype=jnp.int32)
    pos = jnp.full((n_seqs,), ctx - 1, jnp.int32)

    step = jax.jit(lambda q, kp, vp: paged_attention(q, kp, vp, tables, seq_idx, pos, bs, **scales))
    kernel_bucket = f"pallas/paged_attention/s{n_seqs}_ctx{ctx}_{args.kv}"
    rf.register_fn(kernel_bucket, step, q, k_pool, v_pool)
    _sync(step(q, k_pool, v_pool))  # compile
    t0 = time.time()
    for _ in range(reps):
        out = step(q, k_pool, v_pool)
    _sync(out)
    dt_kernel = (time.time() - t0) / reps
    rf.note_wall(kernel_bucket, dt_kernel)
    # analytic KV-stream estimate kept as DISCLOSURE beside the registry's
    # cost_analysis bytes. Factor 2: BOTH the K and V pools stream every
    # step (and both scale pools in int8 mode) — matches bench.py's
    # bench_serving accounting (ADVICE r4: the single-pool count halved the
    # ideal time and under-reported the fraction-of-roofline ~2x)
    kv_bytes = 2 * n_seqs * ctx * nkv * (d * kv_itemsize + (4 if kv_int8 else 0))
    krow = rf.report()["buckets"][kernel_bucket]
    # roofline numerator: XLA's own bytes for the compiled kernel (the same
    # number the serving plane verdicts on); analytic KV stream only when
    # the backend can't price it
    roof_bytes = krow["bytes"] if krow["bytes"] is not None else kv_bytes
    kernel_roofline = roof_bytes / hbm_bw
    print(json.dumps({"metric": "decode_kernel_step_s", "value": round(dt_kernel, 6),
                      "kv_bytes_per_layer": kv_bytes, "kv": args.kv,
                      "cost_bytes": krow["bytes"], "mbu": krow["mbu"],
                      "verdict": krow["verdict"], "assumed_roof": assumed_roof,
                      "vs_roofline": round(kernel_roofline / max(dt_kernel, 1e-12), 4)}))

    # ---- 2/3. engine decode: horizon sweep ----
    icfg = RaggedInferenceEngineConfig()
    icfg.kv_block_size = bs
    icfg.num_kv_blocks = NB + n_seqs * 2
    icfg.kv_dtype = "int8" if kv_int8 else cfg.dtype
    icfg.state_manager.max_tracked_sequences = n_seqs
    icfg.state_manager.max_ragged_sequence_count = n_seqs
    icfg.state_manager.max_ragged_batch_size = max(ctx, n_seqs)
    icfg.state_manager.max_context = ctx + 256
    engine = InferenceEngineV2(TransformerLM(cfg), icfg)
    prompts = [rng.integers(0, cfg.vocab_size, size=ctx, dtype=np.int32) for _ in range(n_seqs)]
    uids = list(range(n_seqs))
    toks = [np.asarray([int(engine.put([u], [prompts[u]], sample="greedy")[0])], np.int32)
            for u in uids]

    param_bytes = engine.module.num_params() * (2 if on_tpu else 4)
    step_kv_bytes = L * kv_bytes
    step_roofline = (param_bytes + step_kv_bytes) / hbm_bw
    for horizon in ([8, 16, 32, 64, 128] if on_tpu else [2, 4]):
        engine.decode(uids, toks, horizon)  # compile
        t0 = time.time()
        out = engine.decode(uids, toks, horizon)
        _sync(out)
        dt = time.time() - t0
        per_step = dt / horizon
        # the engine's compile site registered this decode bucket with the
        # plane (rf is armed), so the registry's cost-model bytes price the
        # whole-horizon scan; null on a backend without cost analysis
        hrow = next((r for bkt, r in rf.report()["buckets"].items()
                     if bkt.startswith("decode/") and bkt.endswith(f"/n{horizon}")), None)
        cost_bytes = hrow["bytes"] if hrow else None
        xla_roofline = (cost_bytes / horizon / hbm_bw) if cost_bytes is not None else None
        print(json.dumps({
            "metric": "decode_horizon_step_s", "horizon": horizon, "kv": args.kv,
            "per_step_s": round(per_step, 6),
            "tokens_per_s": round(n_seqs * horizon / dt, 1),
            "vs_roofline": round(step_roofline / max(per_step, 1e-12), 4),
            "vs_roofline_xla": (round(xla_roofline / max(per_step, 1e-12), 4)
                                if xla_roofline is not None else None),
            "verdict": hrow["verdict"] if hrow else None,
        }))
    # host dispatch estimate: time of a horizon-H call minus H * best per-step
    print(json.dumps({"metric": "decode_step_roofline_s", "value": round(step_roofline, 6),
                      "param_bytes": param_bytes, "kv_bytes": step_kv_bytes,
                      "kv": args.kv, "assumed_roof": assumed_roof}))


if __name__ == "__main__":
    main()
