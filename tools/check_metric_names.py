"""Static check: metric-namespace discipline across the whole package.

Companion to ``check_timed_ops.py`` / ``check_kv_blocks.py`` (same lesson:
structural invariants rot silently unless CI asserts them). Four
observability PRs accumulated metric names by convention only — and the
convention had already drifted twice (``compile/*``, ``data/*``) before
this gate pinned it. The rule: every ``counter`` / ``gauge`` / ``histogram``
registration uses a ``subsystem/name`` snake_case literal whose subsystem
comes from the approved prefix set:

    train / serving / gateway / health / comm / checkpoint / cache / memory
    / goodput / profile / handoff

AST-checked with no package imports, so the gate runs anywhere:

  * a literal first argument must match
    ``^(<prefix>)/[a-z0-9_]+$`` exactly;
  * an f-string first argument must START with an approved ``prefix/`` run
    of snake_case (``f"health/stall_{source}_total"`` passes), and every
    literal fragment must stay in the snake_case charset — dynamic
    interpolation is for per-class/per-source suffixes, never the prefix;
  * a fully dynamic name (a variable) is allowed ONLY in the allowlisted
    plumbing modules that forward caller-validated names
    (``monitor/trace.py``'s ``observe_latency`` tail,
    ``serving/reqtrace.py``'s stage table). Anywhere else it is a
    violation: pass the literal to the registration site, where this gate
    can see it;
  * ``observe_latency(..., hist_name="...", gauges={"...": ...})`` call
    sites are validated too — that plumbing registers whatever it is
    handed.

A tier-1 test (``tests/test_cache_telemetry.py``) runs this on every CI
pass.
"""

import ast
import os
import re
import sys

DEFAULT_PKG_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir,
                               "deepspeed_tpu")

APPROVED_PREFIXES = ("train", "serving", "gateway", "health", "comm",
                     "checkpoint", "cache", "memory", "goodput", "profile",
                     "handoff", "control", "timeline")

REGISTRATION_CALLS = ("counter", "gauge", "histogram")

# modules whose registration sites legitimately take a VARIABLE name: they
# are plumbing that forwards names already validated at the (literal)
# caller site this gate checks
DYNAMIC_ALLOWED = (
    os.path.join("monitor", "trace.py"),
    os.path.join("serving", "reqtrace.py"),
)

_FULL_NAME = re.compile(r"^(%s)/[a-z0-9_]+$" % "|".join(APPROVED_PREFIXES))
_FSTRING_HEAD = re.compile(r"^(%s)/[a-z0-9_]*$" % "|".join(APPROVED_PREFIXES))
_SNAKE_FRAGMENT = re.compile(r"^[a-z0-9_/]*$")


def _literal_ok(name):
    return bool(_FULL_NAME.match(name))


def _joined_str_ok(node):
    """f-string names: approved-prefix literal head, snake_case fragments."""
    parts = node.values
    if not parts or not isinstance(parts[0], ast.Constant) \
            or not isinstance(parts[0].value, str):
        return False
    if not _FSTRING_HEAD.match(parts[0].value):
        return False
    for p in parts[1:]:
        if isinstance(p, ast.Constant):
            if not isinstance(p.value, str) or not _SNAKE_FRAGMENT.match(p.value):
                return False
    return True


def _name_arg_violation(arg, rel, allow_dynamic):
    """Reason string when a metric-name expression breaks the rule, else None."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        if not _literal_ok(arg.value):
            return f"metric name {arg.value!r} not <approved-prefix>/snake_case"
        return None
    if isinstance(arg, ast.JoinedStr):
        if not _joined_str_ok(arg):
            return "f-string metric name must start with an approved 'prefix/' literal"
        return None
    if allow_dynamic:
        return None
    return "non-literal metric name outside the allowlisted plumbing modules"


def find_violations(pkg_dir=DEFAULT_PKG_DIR):
    """[(relpath, lineno, snippet, why)] for every off-convention
    registration under the package tree."""
    violations = []
    for root, _dirs, files in os.walk(pkg_dir):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, pkg_dir)
            allow_dynamic = rel in DYNAMIC_ALLOWED
            with open(path) as f:
                src = f.read()
            tree = ast.parse(src, filename=path)
            lines = src.splitlines()

            def flag(node, why):
                snippet = lines[node.lineno - 1].strip() if node.lineno <= len(lines) else ""
                violations.append((rel, node.lineno, snippet, why))

            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                # direct registrations: <registry>.counter/gauge/histogram(name)
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in REGISTRATION_CALLS and node.args):
                    why = _name_arg_violation(node.args[0], rel, allow_dynamic)
                    if why:
                        flag(node, why)
                # plumbing call sites: hist_name= / gauges={...} keywords
                for kw in node.keywords:
                    if kw.arg == "hist_name" and isinstance(kw.value, ast.Constant) \
                            and isinstance(kw.value.value, str):
                        if not _literal_ok(kw.value.value):
                            flag(node, f"hist_name {kw.value.value!r} not "
                                       "<approved-prefix>/snake_case")
                    elif kw.arg == "gauges" and isinstance(kw.value, ast.Dict):
                        for key in kw.value.keys:
                            if isinstance(key, ast.Constant) and isinstance(key.value, str) \
                                    and not _literal_ok(key.value):
                                flag(node, f"gauges key {key.value!r} not "
                                           "<approved-prefix>/snake_case")
    return violations


def check(pkg_dir=DEFAULT_PKG_DIR):
    """Return the violation list (empty = every registration is in-namespace)."""
    return find_violations(pkg_dir)


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    pkg_dir = argv[0] if argv else DEFAULT_PKG_DIR
    bad = check(pkg_dir)
    if bad:
        print(f"check_metric_names: off-convention metric registrations in {pkg_dir}:")
        for rel, lineno, snippet, why in bad:
            print(f"  {rel}:{lineno}: {why}\n      {snippet}")
        return 1
    print("check_metric_names: every metric registration uses an approved "
          "subsystem/name literal")
    return 0


if __name__ == "__main__":
    sys.exit(main())
