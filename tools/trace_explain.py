"""Differential timeline explain: WHICH stage owns a round-over-round delta.

``tools/perf_sentinel.py`` says THAT a headline moved; this tool says WHY —
it diffs two captured timeline populations (``monitor/timeline.py``'s
``explain_delta``) and names the stage and cause that own the end-to-end
delta. The canonical producer is ``tools/serving_load.py timeline``, which
writes one round file per arm; any file of the same shape works:

    {"meta": {"backend": "cpu"|"tpu", "chip": ..., ...},
     "timelines": [<assembled RequestTimeline dicts>, ...]}

Comparability discipline is inherited, not reimplemented: the same
``bench.comparability_refusal`` that gates the perf sentinel's ratios
refuses cross-backend / cross-chip timeline diffs here (a CPU-fallback
round's stage profile explains nothing about an on-chip regression — the
BENCH_r04/r05 lesson applies to stage attribution exactly as it does to
headlines).

Usage::

    python tools/trace_explain.py BASE.json CUR.json

Exit codes: 0 = explained, 1 = bad input, 2 = comparison refused.
"""

import json
import os
import sys

# `python tools/trace_explain.py` puts tools/ first on sys.path; the
# repo root (bench.py, deepspeed_tpu/) must be importable too
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from bench import comparability_refusal  # noqa: E402
from deepspeed_tpu.monitor.timeline import explain_delta  # noqa: E402


def load_round(path: str) -> dict:
    """One captured round: ``{"meta": {...}, "timelines": [...]}``. A bare
    timeline list is accepted (meta-less — only comparable to another
    meta-less capture if the caller forces it; the refusal will fire)."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, list):
        return {"meta": {}, "timelines": data}
    if not isinstance(data, dict) or "timelines" not in data:
        raise ValueError(f"{path}: expected a round object with a "
                         "'timelines' list (or a bare timeline list)")
    return {"meta": dict(data.get("meta") or {}),
            "timelines": list(data["timelines"])}


def explain(base_round: dict, cur_round: dict) -> dict:
    """The differential verdict, or a refusal. Returns ``explain_delta``'s
    report plus ``refused`` (None = the diff is meaningful)."""
    refusal = comparability_refusal(base_round.get("meta") or {},
                                    cur_round.get("meta") or {})
    if refusal is not None:
        return {"refused": refusal}
    report = explain_delta(base_round["timelines"], cur_round["timelines"])
    report["refused"] = None
    report["base_meta"] = base_round.get("meta") or {}
    report["cur_meta"] = cur_round.get("meta") or {}
    return report


def _fmt_rows(rows, top=5):
    ranked = sorted(rows.items(), key=lambda kv: -abs(kv[1]["delta_ms"]))[:top]
    return [f"    {name:>16}: {r['base_mean_ms']:9.3f} -> {r['cur_mean_ms']:9.3f} ms "
            f"({r['delta_ms']:+9.3f}"
            + (f", {r['share']:+.0%} of delta" if r["share"] is not None else "")
            + ")"
            for name, r in ranked]


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 2:
        print("usage: python tools/trace_explain.py BASE.json CUR.json",
              file=sys.stderr)
        return 1
    try:
        base_round = load_round(argv[0])
        cur_round = load_round(argv[1])
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"trace_explain: {e}", file=sys.stderr)
        return 1
    report = explain(base_round, cur_round)
    print(json.dumps(report, indent=2, default=repr))
    if report["refused"] is not None:
        print(f"\ntrace_explain: REFUSED: {report['refused']}", file=sys.stderr)
        return 2
    print(f"\ntrace_explain: {report['n_base']} base vs {report['n_cur']} cur "
          f"timelines; mean e2e {report.get('base_e2e_mean_ms')} -> "
          f"{report.get('cur_e2e_mean_ms')} ms "
          f"({report['delta_e2e_ms']:+.3f} ms)")
    print(f"  dominant stage: {report['dominant_stage']}   "
          f"dominant cause: {report['dominant_cause']}")
    print("  by stage (top movers):")
    print("\n".join(_fmt_rows(report["by_stage"])))
    print("  by cause (top movers):")
    print("\n".join(_fmt_rows(report["by_cause"])))
    return 0


if __name__ == "__main__":
    sys.exit(main())
