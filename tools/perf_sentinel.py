"""Machine reader for the driver's ``BENCH_r*.json`` round wrappers.

The r01→rNN benchmark trajectory has been sitting on disk as opaque wrapper
files (``{"n": <round>, "cmd": ..., "rc": ..., "tail": ..., "parsed": {...}}``)
with no machine reader — the r03 regression (rc=1, no parsed payload) and the
r04/r05 backend flip (CPU fallback silently incomparable to the on-chip
r01/r02 numbers) were only visible to a human reading prose. This tool:

  * loads every round wrapper under a directory (``BENCH_r01.json`` ...),
    tolerating failed rounds (``rc != 0`` / ``parsed: null`` become explicit
    gap entries, never crashes);
  * flattens each round's parsed bench JSON into dotted scalar metrics
    (``serving.value``, ``serving.ttft_p50_ms``, ``value``, ...) and
    aggregates the per-metric series across rounds;
  * emits a regression verdict per metric over the LAST comparable pair of
    rounds — reusing ``bench.comparability_refusal`` (the cross-backend /
    cross-chip refusal core of ``compare_to_baseline``), so a backend flip
    yields ``verdict: "refused"`` with the reason instead of a bogus ratio;
  * knows metric direction by suffix (``*_ms``/``*_s``/``*_bytes`` lower is
    better; ``*tok_s``/``*_rate``/``value``/``mfu``/``speedup`` higher is
    better; anything else is reported informationally as
    ``unknown_direction``).

Runnable in CI (``python tools/perf_sentinel.py [dir] [--out v.json]
[--threshold 0.9] [--strict]``; ``--strict`` exits 1 on regressions) and
from ``bench.py --history``.
"""

import argparse
import glob
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")

# metric-direction tables: suffix (or exact-name) match on the LAST dotted
# component. The SPECIFIC throughput suffixes are checked first: a name like
# ``decode_tok_s`` also ends in the generic ``_s`` latency suffix and must
# not be read as lower-is-better.
LOWER_BETTER_SUFFIXES = ("_ms", "_s", "_bytes", "_seconds", "_blocked_ratio")
HIGHER_BETTER_SUFFIXES = ("tok_s", "_rate", "_mfu", "_mbu", "speedup",
                          "_tokens_per_sec")
HIGHER_BETTER_NAMES = ("value", "mfu", "mbu", "accept_rate", "hit_rate", "ratio",
                       # tiered-cache bench leaves: reuse the cache hierarchy
                       # can serve at all (HBM + host + disk) vs HBM alone
                       "hierarchy_hit_rate", "hbm_hit_rate")

# wall-clock ACCOUNTING fields, not performance metrics: a longer bench run
# is not a regression. The whole goodput block is attribution (its *_s
# leaves would otherwise hit the generic latency rule), as are the
# disclosure leaves wherever they appear. The tenants block mirrors the
# goodput neutrality rule: per-tenant counters/seconds are ATTRIBUTION of
# whatever the round consumed (a different tenant mix is not a
# regression) — only its fairness index carries a direction.
NEUTRAL_PREFIXES = ("goodput.", "tenants.", "roofline.",
                    # timeline rounds are ATTRIBUTION captures: counts of
                    # assembled/migrated timelines and the seeded-stall
                    # delta are accounting of what the round did, not a
                    # performance verdict (the verdict is the dominant
                    # stage naming the seeded stage, checked in tests)
                    "timeline.")
NEUTRAL_NAMES = ("wall_s", "unattributed_s", "overbooked_s", "recovery_badput_s",
                 # tier migration volume is workload attribution, not a verdict:
                 # more demotions under the same load is the tier doing its job
                 "demotions", "promotions", "host_evictions", "disk_spills",
                 # control-plane actuation counts are the loop reacting to
                 # whatever the round threw at it — more (or fewer) decisions
                 # under a different load is not a verdict; the verdict leaf
                 # is slo_miss_rate below
                 "actuations", "deferred")

# direction overrides that win over the neutral prefixes: the fairness
# index inside the tenants block IS a performance verdict (higher = the
# fleet shares capacity more evenly under the same adversarial load), and
# the roofline block's utilizations are too (higher = closer to the roof) —
# its flop/byte/wall accounting stays neutral
HIGHER_BETTER_LEAVES = ("fairness_index", "mfu", "mbu")

# explicit lower-is-better leaves that the suffix rules would misread:
# ``handoff_fallback_rate`` ends in ``_rate`` (generically higher-better for
# throughput rates) but a FALLING-back migration pipeline is a regressing
# one, and ``handoff_p50_ms`` must stay lower-better even if the generic
# latency suffix table ever changes — both pinned by tests/test_disagg.py
LOWER_BETTER_LEAVES = ("handoff_p50_ms", "handoff_fallback_rate")

# lower-is-better SUFFIX overrides checked before the generic suffix
# tables: any ``*_miss_rate`` (SLO misses, cache misses) ends in ``_rate``
# but a rising miss rate is a regressing system — the control plane's
# audit leaves (``control.slo_miss_rate_*``) ride this rule
LOWER_BETTER_SUFFIX_OVERRIDES = ("_miss_rate",)


def metric_direction(metric):
    """'lower' | 'higher' | None (unknown/neutral) for a dotted name."""
    leaf = metric.rsplit(".", 1)[-1]
    if leaf in HIGHER_BETTER_LEAVES:
        return "higher"
    if leaf in LOWER_BETTER_LEAVES or leaf.endswith(LOWER_BETTER_SUFFIX_OVERRIDES):
        return "lower"
    if metric.startswith(NEUTRAL_PREFIXES) or leaf in NEUTRAL_NAMES:
        return None
    if leaf.endswith(HIGHER_BETTER_SUFFIXES) or leaf in HIGHER_BETTER_NAMES:
        return "higher"
    if leaf.endswith(LOWER_BETTER_SUFFIXES):
        return "lower"
    return None


def read_rounds(bench_dir):
    """[(round_n, wrapper_dict)] sorted by round, one entry per
    ``BENCH_r*.json`` — failed rounds keep their wrapper (``parsed`` None)."""
    rounds = []
    for path in glob.glob(os.path.join(bench_dir, "BENCH_r*.json")):
        m = _ROUND_RE.search(os.path.basename(path))
        if not m:
            continue
        try:
            with open(path) as f:
                wrap = json.load(f)
        except (OSError, ValueError) as e:
            wrap = {"rc": None, "parsed": None,
                    "read_error": f"{type(e).__name__}: {e}"}
        if not isinstance(wrap, dict):
            wrap = {"rc": None, "parsed": None, "read_error": "not a JSON object"}
        n = wrap.get("n", int(m.group(1)))
        rounds.append((int(n), wrap))
    rounds.sort(key=lambda t: t[0])
    return rounds


def flatten_metrics(parsed, prefix=""):
    """Nested bench JSON -> {dotted_name: float} over numeric scalar leaves
    (bools/strings/lists skipped; lists are workload detail, not series)."""
    out = {}
    if not isinstance(parsed, dict):
        return out
    for key, val in parsed.items():
        name = f"{prefix}{key}"
        if isinstance(val, bool):
            continue
        if isinstance(val, (int, float)):
            out[name] = float(val)
        elif isinstance(val, dict):
            out.update(flatten_metrics(val, prefix=name + "."))
    return out


def metric_series(rounds):
    """{metric: [(round_n, value)]} over the successfully parsed rounds."""
    series = {}
    for n, wrap in rounds:
        parsed = wrap.get("parsed")
        if not isinstance(parsed, dict):
            continue
        for metric, val in flatten_metrics(parsed).items():
            series.setdefault(metric, []).append((n, val))
    return series


def _verdict(metric, prev, cur, ratio, threshold):
    direction = metric_direction(metric)
    if direction is None:
        return "unknown_direction"
    # threshold is the tolerated fractional change in the BAD direction
    # (0.9 => flag a >10% move for the worse); the GOOD direction mirrors it
    if direction == "higher":
        if ratio < threshold:
            return "regressed"
        if ratio > 1.0 / threshold:
            return "improved"
    else:
        if ratio > 1.0 / threshold:
            return "regressed"
        if ratio < threshold:
            return "improved"
    return "ok"


def trajectory_verdicts(bench_dir, threshold=0.9):
    """The full machine-readable trajectory report:

    ``rounds``: per-round status (rc, backend, headline value, gaps named);
    ``series``: per-metric [(round, value)] across parsed rounds;
    ``verdicts``: one row per metric over the last ADJACENT parsed pair —
    {metric, prev_round, cur_round, prev, cur, ratio, verdict} with
    cross-backend/cross-chip pairs refused (reason in ``refused``), the
    same refusal logic ``bench.compare_to_baseline`` applies to headlines.
    """
    from bench import comparability_refusal, backend_of

    rounds = read_rounds(bench_dir)
    round_rows = []
    for n, wrap in rounds:
        parsed = wrap.get("parsed")
        row = {"round": n, "rc": wrap.get("rc"),
               "parsed": isinstance(parsed, dict)}
        if isinstance(parsed, dict):
            row["backend"] = backend_of(parsed)
            row["chip"] = parsed.get("chip")
            row["metric"] = parsed.get("metric")
            row["value"] = parsed.get("value")
        elif "read_error" in wrap:
            row["error"] = wrap["read_error"]
        round_rows.append(row)

    parsed_rounds = [(n, w["parsed"]) for n, w in rounds
                     if isinstance(w.get("parsed"), dict)]
    series = metric_series(rounds)
    verdicts = []
    if len(parsed_rounds) >= 2:
        (pn, prev_parsed), (cn, cur_parsed) = parsed_rounds[-2], parsed_rounds[-1]
        refusal = comparability_refusal(prev_parsed, cur_parsed)
        prev_m = flatten_metrics(prev_parsed)
        cur_m = flatten_metrics(cur_parsed)
        for metric in sorted(set(prev_m) & set(cur_m)):
            prev, cur = prev_m[metric], cur_m[metric]
            row = {"metric": metric, "prev_round": pn, "cur_round": cn,
                   "prev": prev, "cur": cur}
            if refusal is not None:
                row.update({"ratio": None, "verdict": "refused", "refused": refusal})
            elif prev == 0:
                row.update({"ratio": None, "verdict": "unknown_direction"})
            else:
                ratio = cur / prev
                row.update({"ratio": round(ratio, 4),
                            "verdict": _verdict(metric, prev, cur, ratio, threshold)})
            verdicts.append(row)
    regressions = [v for v in verdicts if v["verdict"] == "regressed"]
    return {
        "bench_dir": os.path.abspath(bench_dir),
        "threshold": threshold,
        "rounds": round_rows,
        "series": {m: s for m, s in sorted(series.items())},
        "verdicts": verdicts,
        "regressions": len(regressions),
        "refused": sum(1 for v in verdicts if v["verdict"] == "refused"),
    }


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Regression sentinel over the BENCH_r*.json round trajectory")
    p.add_argument("bench_dir", nargs="?",
                   default=os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                        os.pardir))
    p.add_argument("--out", default=None, help="write the full verdict JSON here")
    p.add_argument("--threshold", type=float, default=0.9,
                   help="tolerated worse-direction ratio (0.9 = flag >10%% regressions)")
    p.add_argument("--strict", action="store_true",
                   help="exit 1 when any metric regressed")
    args = p.parse_args(argv)

    report = trajectory_verdicts(args.bench_dir, threshold=args.threshold)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    n_rounds = len(report["rounds"])
    parsed = sum(1 for r in report["rounds"] if r["parsed"])
    print(f"# perf_sentinel: {n_rounds} rounds ({parsed} parsed), "
          f"{len(report['verdicts'])} metrics compared, "
          f"{report['regressions']} regressed, {report['refused']} refused")
    for v in report["verdicts"]:
        if v["verdict"] in ("regressed", "improved", "refused"):
            detail = (f"ratio={v['ratio']}" if v.get("ratio") is not None
                      else v.get("refused", ""))
            print(f"#   {v['verdict']:9s} {v['metric']}: "
                  f"{v['prev']} -> {v['cur']} ({detail})")
    print(json.dumps({"regressions": report["regressions"],
                      "refused": report["refused"],
                      "rounds": n_rounds}))
    return 1 if (args.strict and report["regressions"]) else 0


if __name__ == "__main__":
    sys.exit(main())
