"""Config-ladder benchmark — the BASELINE.md:24-25 ladder points beyond the
driver-gated ``bench.py`` headline (which measures the ZeRO-3 proxy +
FastGen serving).

Not run by the driver (its 550s budget gates ``bench.py`` alone); run
manually, results recorded in COVERAGE.md. Single-chip proxies are labeled
as such: the 70B/pod-scale ladder rungs need hardware this environment
doesn't expose (their sharding compiles in ``__graft_entry__.dryrun_multichip``).

  1. BERT-base-size ZeRO-1 (110M, layernorm/gelu/learned-positions arch —
     causal-LM proxy of the encoder workload, disclosed)
  2. MoE 4-expert top-1 training (gating + dispatch overhead vs dense)
  3. Long-context seq-8192 ZeRO-3 with flash attention + remat

Each line: {"config": ..., "tokens_per_sec_per_chip": ..., "mfu": ...}
"""

import json
import time


def train_tps(cfg, micro, gas, seq, steps, warmup, stage, n_params_known=None,
              zero_override=None, bf16=True):
    import numpy as np
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerLM
    from deepspeed_tpu.parallel import groups

    groups.reset()
    model = TransformerLM(cfg)
    n_chips = len(jax.devices())
    config = {
        "train_batch_size": micro * gas * n_chips,
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.0}},
        "zero_optimization": zero_override if zero_override is not None else {"stage": stage},
        "bf16": {"enabled": bf16},
        "steps_per_print": 10**9,
        "tpu": {"mesh": {"data": n_chips}},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, size=(config["train_batch_size"], seq),
                                       dtype=np.int32)}
    for _ in range(warmup):
        engine.train_batch(batch)
    float(np.asarray(engine.state["step"]))
    t0 = time.time()
    for _ in range(steps):
        engine.train_batch(batch)
    float(np.asarray(engine.state["step"]))
    tps = steps * config["train_batch_size"] * seq / (time.time() - t0) / n_chips
    n_params = model.num_params()
    engine.state = None
    engine._compiled = {}
    del engine
    import gc

    gc.collect()
    return tps, n_params


def rlhf_hybrid_bench(on_tpu: bool):
    """RLHF actor loop: N x (train_batch -> generate rollouts) under the
    hybrid engine. Reports rollout decode tokens/s and the per-flip overhead
    (generate latency under interleave vs back-to-back generates on the same
    engine — the cost the reference's inference-container rebuild pays,
    hybrid_engine.py:174)."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, TransformerLM
    from deepspeed_tpu.parallel import groups

    groups.reset()
    if on_tpu:
        cfg = TransformerConfig(vocab_size=32000, hidden_size=2048, num_layers=12,
                                num_heads=16, num_kv_heads=16, intermediate_size=5632,
                                max_seq_len=1024, norm="rmsnorm", positions="rotary",
                                mlp="swiglu", dtype=jnp.bfloat16, attention_impl="flash",
                                remat=True, remat_policy="save_only_these_names(attn_out)")
        micro, prompts, prompt_len, new_tokens, rounds = 2, 8, 256, 128, 4
    else:
        cfg = TransformerConfig(vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
                                intermediate_size=256, max_seq_len=256, dtype=jnp.float32,
                                attention_impl="reference")
        micro, prompts, prompt_len, new_tokens, rounds = 2, 2, 16, 8, 2
    model = TransformerLM(cfg)
    n_chips = len(jax.devices())
    config = {
        "train_batch_size": micro * n_chips,
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-5}},
        "zero_optimization": {"stage": 3 if on_tpu else 0},
        "bf16": {"enabled": bool(on_tpu)},
        "hybrid_engine": {"enabled": True},
        "steps_per_print": 10**9,
        "tpu": {"mesh": {"data": n_chips}},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    rng = np.random.default_rng(0)
    seq = min(cfg.max_seq_len, 512)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size,
                                       size=(config["train_batch_size"], seq), dtype=np.int32)}
    prompt = rng.integers(0, cfg.vocab_size, size=(prompts, prompt_len), dtype=np.int32)

    engine.train_batch(batch)           # compile train
    engine.generate(prompt, max_new_tokens=new_tokens)  # compile generate
    # back-to-back generates: the no-flip baseline
    t0 = time.time()
    engine.generate(prompt, max_new_tokens=new_tokens)
    engine.generate(prompt, max_new_tokens=new_tokens)
    pure_gen_s = (time.time() - t0) / 2
    # the RLHF interleave: every generate pays the param-reshard flip
    t0 = time.time()
    for _ in range(rounds):
        engine.train_batch(batch)
        engine.generate(prompt, max_new_tokens=new_tokens)
    total = time.time() - t0
    lat = engine.generate_latency()
    flip_gen_s = float(np.mean(lat[-rounds:]))
    rollout_tps = prompts * new_tokens / flip_gen_s
    return {
        "config": "rlhf_hybrid_generate",
        "rollout_tokens_per_sec": round(rollout_tps, 1),
        "generate_s_interleaved": round(flip_gen_s, 3),
        "generate_s_back_to_back": round(pure_gen_s, 3),
        "flip_overhead_pct": round(100 * (flip_gen_s - pure_gen_s) / max(pure_gen_s, 1e-9), 1),
        "train_plus_generate_round_s": round(total / rounds, 3),
    }


def offload_ratio_sweep(on_tpu: bool):
    """tokens/s vs ``offload_optimizer.ratio`` (plus the no-offload bound).
    The twin-flow claim is throughput recovery: the device slice updates in
    HBM concurrently with the host C++ Adam on the rest. Reuses train_tps —
    one timing harness for every ladder rung."""
    import jax.numpy as jnp

    from deepspeed_tpu.models import TransformerConfig

    if on_tpu:
        cfg = TransformerConfig(vocab_size=32000, hidden_size=2048, num_layers=12,
                                num_heads=16, num_kv_heads=16, intermediate_size=5632,
                                max_seq_len=1024, norm="rmsnorm", positions="rotary",
                                mlp="swiglu", dtype=jnp.bfloat16, attention_impl="flash",
                                remat=True, remat_policy="save_only_these_names(attn_out)")
        micro, seq, steps, warmup = 4, 1024, 4, 2
    else:
        cfg = TransformerConfig(vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
                                intermediate_size=256, max_seq_len=256, dtype=jnp.float32,
                                attention_impl="reference")
        micro, seq, steps, warmup = 2, 128, 2, 1

    def tps(ratio):
        zero = {"stage": 2}
        if ratio is not None:
            zero["offload_optimizer"] = {"device": "cpu", "ratio": ratio}
        out, _ = train_tps(cfg, micro=micro, gas=1, seq=seq, steps=steps, warmup=warmup,
                           stage=2, zero_override=zero, bf16=bool(on_tpu))
        return round(out, 1)

    result = {"config": "offload_twin_flow_sweep",
              "tokens_per_sec_per_chip": {
                  "no_offload": tps(None),
                  "ratio_1.0": tps(1.0),
                  "ratio_0.5": tps(0.5),
                  "ratio_0.2": tps(0.2)}}
    full, half = result["tokens_per_sec_per_chip"]["ratio_1.0"], \
        result["tokens_per_sec_per_chip"]["ratio_0.5"]
    result["twin_flow_speedup_vs_full_offload"] = round(half / max(full, 1e-9), 3)
    return result


def main():
    import os

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # sitecustomize's config-level jax_platforms="axon,cpu" beats the env
        # var; honor an explicit CPU pin instead of hanging on a dead TPU
        # tunnel (same guard as bench.py)
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from deepspeed_tpu.models import TransformerConfig

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    peak = 197e12 if on_tpu else 1e12

    ladder = []
    if on_tpu:
        ladder = [
            ("bert_base_zero1_proxy", TransformerConfig(
                vocab_size=30522, hidden_size=768, num_layers=12, num_heads=12,
                max_seq_len=512, norm="layernorm", positions="learned", mlp="gelu",
                use_bias=True, tie_embeddings=True, dtype=jnp.bfloat16,
                attention_impl="flash"), dict(micro=16, gas=1, seq=512, steps=12, warmup=2,
                                              stage=1)),
            ("moe_4expert_top1", TransformerConfig(
                vocab_size=32000, hidden_size=1024, num_layers=8, num_heads=16,
                max_seq_len=1024, dtype=jnp.bfloat16, attention_impl="flash",
                moe_num_experts=4, moe_top_k=1), dict(micro=4, gas=2, seq=1024, steps=8,
                                                      warmup=2, stage=2)),
            # 8 layers, not 12: the 748M model's fp32 Adam states + f32 grad
            # accumulator leave no HBM headroom for seq-8192 activations on
            # one 16G chip (measured 16.40G demand)
            ("longctx_seq8192_zero3", TransformerConfig(
                vocab_size=32000, hidden_size=2048, num_layers=8, num_heads=16,
                intermediate_size=5632, max_seq_len=8192, dtype=jnp.bfloat16,
                attention_impl="flash", remat=True,
                remat_policy="save_only_these_names(attn_out)"), dict(micro=1, gas=2,
                                                                      seq=8192, steps=4,
                                                                      warmup=1, stage=3)),
            # seq 16k: needs BOTH the streaming flash forward (S-independent
            # VMEM) and chunked CE (full [S, V] fp32 logits would be 2GiB)
            ("longctx_seq16384_zero3", TransformerConfig(
                vocab_size=32000, hidden_size=2048, num_layers=8, num_heads=16,
                intermediate_size=5632, max_seq_len=16384, dtype=jnp.bfloat16,
                attention_impl="flash", remat=True, loss_chunk=2048,
                remat_policy="save_only_these_names(attn_out)"), dict(micro=1, gas=1,
                                                                      seq=16384, steps=3,
                                                                      warmup=1, stage=3)),
        ]
    else:  # CPU smoke: one tiny config proves the script runs
        ladder = [("cpu_smoke", TransformerConfig(
            vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
            intermediate_size=256, max_seq_len=256, dtype=jnp.float32,
            attention_impl="reference"), dict(micro=2, gas=1, seq=256, steps=2, warmup=1,
                                              stage=1))]

    import sys

    wanted = sys.argv[1:]

    # serving rung: FastGen-style continuous-batching load test — Dynamic
    # SplitFuse vs static batching on the same engine (reference methodology
    # blogs/deepspeed-fastgen/README.md:139-144; VERDICT r4 missing #3)
    if not wanted or any(w in "serving_load_splitfuse_vs_static" for w in wanted):
        from tools.serving_load import serving_load_bench

        out = serving_load_bench(on_tpu)
        out["on_tpu"] = on_tpu
        print(json.dumps(out), flush=True)

    # RLHF hybrid-engine rung (reference README.md:16 15x claim is about
    # generate-phase throughput INSIDE training; VERDICT r4 weak #6): ZeRO-3
    # train + generate interleave, reporting rollout tokens/s and the flip
    # overhead vs a pure-inference engine on the same weights
    if not wanted or any(w in "rlhf_hybrid_generate" for w in wanted):
        out = rlhf_hybrid_bench(on_tpu)
        out["on_tpu"] = on_tpu
        print(json.dumps(out), flush=True)

    # ZeRO-Offload++ twin-flow rung (reference blogs/deepspeed-offloadpp 6x
    # claim): tokens/s at offload ratio 1.0 (full host Adam) vs 0.5 vs 0.2 —
    # the HBM slice's async update should recover throughput toward the
    # no-offload bound as the ratio drops
    if not wanted or any(w in "offload_twin_flow_sweep" for w in wanted):
        out = offload_ratio_sweep(on_tpu)
        out["on_tpu"] = on_tpu
        print(json.dumps(out), flush=True)

    for name, cfg, kw in ladder:
        if wanted and not any(w in name for w in wanted):
            continue
        tps, n_params = train_tps(cfg, **kw)
        attn = 12 * cfg.num_layers * cfg.hidden_size * kw["seq"]
        # MoE: FLOPs follow the ACTIVATED expert count, not the total
        # parameter count — scale the expert MLP share down by top_k/E
        n_active = n_params
        if cfg.moe_num_experts > 1:
            # __post_init__ always resolves intermediate_size
            expert_p = cfg.num_layers * 3 * cfg.hidden_size * cfg.intermediate_size * cfg.moe_num_experts
            n_active = n_params - expert_p * (1 - cfg.moe_top_k / cfg.moe_num_experts)
        mfu = tps * (6 * n_active + attn) / peak
        print(json.dumps({"config": name, "tokens_per_sec_per_chip": round(tps, 1),
                          "params_m": round(n_params / 1e6, 1),
                          "active_params_m": round(n_active / 1e6, 1), "mfu": round(mfu, 4)}),
              flush=True)


if __name__ == "__main__":
    main()
